"""Continuous-batching serving engine (multi-request decode).

Ref: the reference serves multi-rank inference through
``fleet_executor/dist_model.cc`` (DistModel — a persistent runtime that
feeds requests through per-stage processes) and a thread-safe
``AnalysisPredictor::ZeroCopyRun`` (``inference/api/analysis_predictor.h:182``)
so many client threads can share one loaded model.

TPU-native design: ONE jitted tick program over a slot-based static KV
cache (``max_slots`` x ``max_len``).  Each tick advances every occupied
slot by up to ``chunk`` tokens — prompt prefill is chunked into the SAME
program that decodes (mixed prefill+decode batching), so a new request
joins mid-flight without recompiling or stalling streams already
decoding.  Per-slot cache depths ride a vector ``cache_pos`` through the
model (``models/gpt.py`` static-cache attention); sampling happens
in-program at each slot's last valid position.  The host side is a slot
scheduler: admit from a FIFO into free slots, stage each slot's next
token chunk, retire finished requests.

``cache_mode="paged"`` swaps the per-slot dense regions for a global
page pool with per-slot page tables (PagedAttention/RadixAttention
lineage): admission reserves each request's actual page footprint
instead of a ``max_len`` slot, a radix prefix cache lets requests
sharing a page-aligned prompt prefix map the same physical pages and
prefill only their suffix, and attention gathers K/V through the table
(``incubate/nn/kernels/paged_attention.py``).  Host-side bookkeeping
lives in ``inference/paged.py``; docs/SERVING.md has the layout diagram
and sizing guidance.

Under pipeline parallelism the tick runs the interleaved-wave schedule:
the slot batch splits into ``pp`` waves, each wave occupying a different
stage every tick, so ALL stages do useful work each tick — the
multi-request bubble-fill that the single-stream masked schedule
(``parallel/pipeline.py pipeline_decode_apply``) documents as "would
fill it".  A wave's sample surfaces ``pp - 1`` ticks after its tokens
enter stage 0; the engine advances a wave's slot state only when its
sample exits, so every stage mid-flight sees the wave's entry-time cache
positions.
"""

from __future__ import annotations

import collections
import collections.abc
import itertools
import threading
import time
import zlib
from typing import List, Optional

import numpy as np

from ..observability import faults as _faults
from ..observability import flight as _flight
from ..observability import metrics as _obs
from ..observability.sanitizers import (make_lock, sanitize_donation,
                                        share_object)
from ..observability import tracing as _tr

_ENGINE_IDS = itertools.count()
_REQ_IDS = itertools.count()

# SLO priority classes (submit(priority=)): lower rank schedules first.
# Aging (ServingEngine priority_aging_s) promotes a waiting request one
# rank per interval, so batch work cannot starve forever under a
# sustained interactive load.
PRIORITY_RANK = {"interactive": 0, "default": 1, "batch": 2}


class _EngineStats(collections.abc.Mapping):
    """Back-compat dict view over the engine's registry counters: the
    historical ``engine.stats`` keys read straight from the labelled
    ``serving_*_total`` series, so existing callers (tests, bench rows)
    keep working while scrapers get the full labelled families."""

    _KEYS = ("ticks", "tokens", "requests",
             "spec_ticks", "spec_drafted", "spec_accepted",
             "prefix_hit_tokens", "prompt_tokens", "prefix_hit_rate",
             "session_resumes", "session_hit_tokens", "preemptions")

    def __init__(self, counters):
        self._counters = counters   # key -> Counter child

    def __getitem__(self, k):
        if k == "prefix_hit_rate":
            # derived: prompt tokens the prefix cache saved re-prefilling
            # over all prompt tokens admitted (0.0 until any admit)
            pt = int(self._counters["prompt_tokens"].value)
            hit = int(self._counters["prefix_hit_tokens"].value)
            return hit / pt if pt else 0.0
        return int(self._counters[k].value)

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def __repr__(self):
        return repr(dict(self))


def _storage_dtype(dtype):
    """npz-safe storage dtype for a param dtype: ml_dtypes extension
    types (bfloat16, float8_*) round-trip through ``np.savez`` as raw
    void blobs ('|V2') that numpy cannot interpret back — store them as
    same-width unsigned ints and record the logical dtype name in
    config.json instead."""
    if dtype.kind == "V" or dtype.name not in np.sctypeDict:
        return np.dtype(f"u{dtype.itemsize}")
    return None


def _named_dtype(name):
    """np.dtype for a recorded dtype name, resolving ml_dtypes extension
    names (e.g. 'bfloat16') that ``np.dtype(str)`` does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


class TornArtifactError(RuntimeError):
    """A serving artifact directory is incomplete — a crash mid-save by
    a pre-atomic writer, or a partial copy.  :func:`save_for_serving`
    commits atomically (tmp dir + rename), so a torn directory is
    always externally produced; :func:`load_for_serving` refuses to
    half-load it."""


def save_for_serving(model, path, quant=None):
    """Persist ``{config.json, params.npz}`` so a serving process — in
    particular the C++ shim (``native/serving.cc pht_engine_create``) —
    can rebuild the model without the training script (the role of the
    reference's ``save_inference_model`` artifact for ``DistModel``).

    ATOMIC: both files land in a tmp directory (``params.npz`` first,
    ``config.json`` — the manifest — last, both fsync'd) which is then
    renamed over ``path``; a crash mid-save leaves the previous artifact
    (or nothing) — never a torn directory a later
    :func:`load_for_serving` would half-load.

    Works for any param dtype: bf16 (the expected serving dtype — the
    bench casts GPT-2 to bf16) and other ml_dtypes store as uint views
    with the logical dtype recorded per param in ``config.json``.

    ``quant="int8"`` (or ``"fp8"``, falling back to int8 where the dtype
    is missing) post-training-quantizes the attention/MLP projection
    weights at save time: the artifact stores int8 values plus f32
    per-output-channel ``<name>_scale`` entries (~halving weight bytes),
    and ``config.json`` records ``{"quant": {"scheme", "params"}}`` so
    :func:`load_for_serving` installs the fused-GEMM serving layers
    before loading state — no wide copy of the SAVED weights is ever
    built (model construction still transiently allocates the default
    f32 initializers, the same load peak as the bf16 path).  A model
    ALREADY holding
    quantized Linears (``nn.quant.convert_to_weight_only`` — the QAT
    export) records the same manifest without ``quant=``; embeddings,
    layernorms and the tied logits head stay in the float dtype either
    way (docs/SERVING.md, "Weight-only quantized serving")."""
    import dataclasses
    import json
    import os
    import shutil
    params = {k: v._value for k, v in model.named_parameters()}
    scheme = None
    if quant is not None:
        from ..nn.quant import weight_only as _wo
        scheme = _wo.resolve_scheme(quant)
        params, _ = _wo.quantize_weights(params, scheme)
    # manifest by inspection (covers both quant= and pre-quantized
    # trees): a weight with a `_scale` sibling is a serving-quantized
    # Linear the loader must swap before loading state
    manifest = sorted(k for k in params if k + "_scale" in params)
    arrs, dtypes = {}, {}
    for k, v in params.items():
        a = np.asarray(v)
        dtypes[k] = a.dtype.name
        store = _storage_dtype(a.dtype)
        arrs[k] = a.view(store) if store is not None else a
    meta = {"model": type(model).__name__,
            "config": dataclasses.asdict(model.config),
            "param_dtypes": dtypes}
    if manifest:
        if scheme is None:
            scheme = ("int8" if dtypes[manifest[0]] == "int8"
                      else "fp8-e4m3")
        meta["quant"] = {"scheme": scheme, "params": manifest}
    # atomic commit: params first, the config manifest last, rename the
    # whole directory into place (same trio as the training checkpoints,
    # parallel/checkpointing.py — docs/CHECKPOINTING.md)
    import uuid
    path = os.fspath(path)
    # pid identifies the owner for the liveness sweep; the uuid keeps
    # concurrent saves from different THREADS of one process (same pid)
    # off each other's tmp dirs
    tmp = f"{path}.saving-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    old = f"{path}.old"
    # sweep tmp dirs orphaned by a DEAD process's hard kill: each holds
    # a full-model-size params.npz nothing else would ever delete.  A
    # dir whose owner pid is still alive (this process included — a
    # concurrent thread's save) is left alone
    import glob as _glob
    for stale in _glob.glob(f"{path}.saving-*"):
        try:
            pid = int(stale.split(".saving-", 1)[1].split("-", 1)[0])
            os.kill(pid, 0)       # raises if the owner is gone
            continue              # owner alive: not ours to sweep
        except (ValueError, ProcessLookupError):
            pass                  # malformed name or dead owner: sweep
        except PermissionError:
            continue              # alive under another uid
        shutil.rmtree(stale, ignore_errors=True)
    os.makedirs(tmp)
    try:
        with open(os.path.join(tmp, "params.npz"), "wb") as f:
            np.savez(f, **arrs)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, "config.json"), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        # carry sidecar files (tokenizer.json etc.) the user keeps next
        # to the framework's two into the replacement — a re-export must
        # not silently destroy them.  After a swap-window crash the live
        # artifact is .old, so sidecars come from there.
        side_src = path if os.path.isdir(path) else (
            old if os.path.isdir(old) else None)
        if side_src is not None:
            for n in os.listdir(side_src):
                if n in ("config.json", "params.npz"):
                    continue
                src, dst = os.path.join(side_src, n), os.path.join(tmp, n)
                if os.path.isdir(src):
                    shutil.copytree(src, dst)
                else:
                    shutil.copy2(src, dst)
        if os.path.isdir(path):
            # `path` is a complete artifact, so a stale .old (leftover
            # of a crash AFTER a previous commit) is disposable.  Never
            # delete .old while it may be the only valid copy — when
            # `path` is missing (crash inside a previous swap window),
            # .old survives until the rename below commits.
            shutil.rmtree(old, ignore_errors=True)
            os.rename(path, old)
        os.rename(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
        # durability of the rename itself (same protocol step as
        # checkpointing._write_checkpoint_dir's root fsync)
        from ..parallel.checkpointing import _fsync_dir
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_for_serving(path):
    """Rebuild the model saved by :func:`save_for_serving`.

    A torn artifact (missing/truncated ``config.json`` or missing
    ``params.npz``) raises :class:`TornArtifactError` instead of
    half-loading; a directory caught between the two renames of an
    atomic re-save falls back to the surviving ``.old`` artifact."""
    import json
    import os

    from ..core.tensor import Tensor
    from ..models import gpt as _gpt
    path = os.fspath(path)
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        # crash inside save_for_serving's swap window: the previous
        # artifact is complete at .old — serve that
        path = path + ".old"
    if not os.path.isdir(path):
        raise FileNotFoundError(path)
    cfg_p = os.path.join(path, "config.json")
    npz_p = os.path.join(path, "params.npz")
    for p in (cfg_p, npz_p):
        if not os.path.exists(p):
            raise TornArtifactError(
                f"serving artifact at {path} is torn: {os.path.basename(p)} "
                f"is missing — the save crashed mid-write (pre-atomic "
                f"writer) or the copy was partial; re-export with "
                f"save_for_serving")
    try:
        with open(cfg_p) as f:
            meta = json.load(f)
    except ValueError as e:
        raise TornArtifactError(
            f"serving artifact at {path} is torn: config.json does not "
            f"parse ({e}) — re-export with save_for_serving") from e
    cls = getattr(_gpt, meta["model"])
    model = cls(_gpt.GPTConfig(**meta["config"]))
    model.eval()
    q = meta.get("quant")
    if q:
        # quantize-at-load: install empty WeightOnlyLinear shells at the
        # manifest paths BEFORE loading state, so the int8/fp8 weights
        # land directly in the fused-GEMM layers — no wide copy of the
        # SAVED weights is ever built.  (Construction above still paid
        # the default f32 initializers transiently — the same load peak
        # as any load_for_serving; the swap frees those right here,
        # before params.npz streams in.)
        from ..nn.quant.weight_only import apply_weight_only
        apply_weight_only(model, q["scheme"], names=q["params"])
    z = np.load(os.path.join(path, "params.npz"))
    dtypes = meta.get("param_dtypes", {})
    state = {}
    for k in z.files:
        a = np.asarray(z[k])
        want = dtypes.get(k)
        if want is not None and a.dtype.name != want:
            a = a.view(_named_dtype(want))
        state[k] = Tensor(a)
    model.set_state_dict(state)
    # set_state_dict casts into the fresh model's (f32) param dtypes;
    # serving wants the SAVED dtypes back (bf16 halves HBM and is the
    # dtype the engine was benched/validated in)
    import jax.numpy as jnp
    for k, p in model.named_parameters():
        want = dtypes.get(k)
        if want is not None and p._value.dtype.name != want:
            p._set_value(p._value.astype(_named_dtype(want)))
    return model


class DeadlineExceededError(RuntimeError):
    """A request blew past its ``submit(deadline_s=)`` budget — either
    still queued (queue-wait is where overload deadlines actually die)
    or mid-decode — and was aborted: waiting longer can only return an
    answer the caller has already given up on.  Generated-so-far tokens
    are counted into ``serving_aborted_tokens_total`` and the lifecycle
    record is stamped ``t_abort``/``where="deadline"`` (also visible in
    ``/debug/requests`` under ``recent_aborts``)."""


class EngineDraining(RuntimeError):
    """:meth:`ServingEngine.submit` was called on a draining engine.
    :meth:`ServingEngine.drain` stops admission while queued + inflight
    requests run to completion — the graceful half of removal (hard
    ``shutdown(timeout=)`` is the other half).  A fleet router treats
    this as "place elsewhere", never as a replica failure."""


class Request:
    """One in-flight generation request.

    ``temperature``/``top_k``/``top_p`` override the engine-global
    sampling defaults for this request only (None = inherit).

    ``lifecycle`` is the request's SLO record — one flat dict stamped at
    each stage (submit → admit → first token → per-tick decode → finish
    or abort), the per-request ground truth behind the rolling window
    percentiles in :meth:`ServingEngine.load_report`.  Times are
    ``time.perf_counter()`` values (the engine's monotonic clock);
    derived durations (``queue_s``/``ttft_s``/``tpot_s``/``e2e_s``)
    land next to them so callers never re-derive.  Plain data on the
    request object, NOT metric labels: per-request ids as labels would
    mint one time series per request and grow the registry without
    bound (pht-lint PHT005).

    ``on_token`` is the per-token streaming hand-off: a callable the
    engine invokes with each committed token id, then exactly once with
    ``None`` at the request's terminal (finish, abort, or loop
    failure).  Calls run on the engine's driver thread AFTER the engine
    lock is released, so a hook that blocks (a bounded queue doing
    backpressure — the fleet router's ``submit_stream``) stalls only
    the decode loop, never ``submit()``/introspection."""

    __slots__ = ("prompt", "max_new_tokens", "tokens", "done", "error",
                 "temperature", "top_k", "top_p", "_event",
                 "_t_submit", "_t_first", "rid", "_span_queue",
                 "_span_life", "lifecycle", "_tick_mark", "deadline_s",
                 "on_token", "session", "priority", "_prank",
                 "_preempts", "_t_queued", "trace_ctx")

    def __init__(self, prompt, max_new_tokens, temperature=None,
                 top_k=None, top_p=None, deadline_s=None, on_token=None,
                 session=None, priority=None, trace_ctx=None):
        self.rid = next(_REQ_IDS)   # process-wide request id (spans/flight)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = None if temperature is None else float(temperature)
        self.top_k = None if top_k is None else int(top_k)
        self.top_p = None if top_p is None else float(top_p)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.on_token = on_token
        self.session = session   # multi-turn KV session key (or None)
        self.priority = "default" if priority is None else priority
        if self.priority not in PRIORITY_RANK:
            raise ValueError(
                f"priority must be one of {sorted(PRIORITY_RANK)}, "
                f"got {priority!r}")
        self._prank = PRIORITY_RANK[self.priority]
        self._preempts = 0   # times this request was preempted (cap)
        self.tokens: List[int] = []  # generated so far
        self.done = False
        self.error: Optional[BaseException] = None
        self._event = threading.Event()
        self._t_submit = time.perf_counter()   # TTFT/e2e reference point
        # last time the request (re-)entered the queue: submit, or a
        # preemption's re-queue — the queue-wait the SLO windows and
        # /load's oldest_wait_s measure (deadlines/aging stay on
        # _t_submit: total-budget semantics)
        self._t_queued = self._t_submit
        self._t_first: Optional[float] = None  # first generated token
        # (last commit time, tokens then) — the per-tick TPOT sample base
        self._tick_mark: Optional[tuple] = None
        self.lifecycle = {"rid": self.rid,
                          "prompt_len": int(self.prompt.shape[0]),
                          "max_new_tokens": self.max_new_tokens,
                          "t_submit": self._t_submit,
                          "priority": self.priority}
        if self.deadline_s is not None:
            self.lifecycle["deadline_s"] = self.deadline_s
        # fleet trace context (docs/OBSERVABILITY.md, "Fleet telemetry"):
        # a plain dict minted by the router — fleet id, fleet-wide
        # request id, dispatch attempt ordinal.  Stamped into the
        # lifecycle record so this replica's view of the request links
        # back to the router decision that placed it (and, post-HTTP,
        # to the header the context will ride in).
        self.trace_ctx = dict(trace_ctx) if trace_ctx else None
        if self.trace_ctx is not None:
            if self.trace_ctx.get("fleet_rid") is not None:
                self.lifecycle["fleet_rid"] = self.trace_ctx["fleet_rid"]
            if self.trace_ctx.get("attempt") is not None:
                self.lifecycle["dispatch_attempt"] = \
                    self.trace_ctx["attempt"]
        # lifecycle spans (no-ops while tracing is disabled): queued =
        # submit->admit, life = submit->finish/EOS
        self._span_queue = self._span_life = _tr._NOOP

    def wait(self, timeout=None):
        self._event.wait(timeout)
        return self.done

    def result(self):
        """Full sequence (prompt + generated), like ``model.generate``."""
        if self.error is not None:
            raise RuntimeError("request failed in the engine") from self.error
        if not self.done:
            raise RuntimeError("request not finished; wait() first")
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])


class _LoadDebugSource:
    """Adapter publishing an engine's :meth:`ServingEngine.load_report`
    through the ``/debug/requests`` introspection registry (as
    ``"<engine>.load"``) so the capacity document is inspectable from
    the debug surface too, not only the router-facing ``/load``.  The
    engine holds the strong reference; the registry holds it weakly."""

    __slots__ = ("_engine", "__weakref__")

    def __init__(self, engine):
        self._engine = engine

    def introspect_requests(self) -> dict:
        return self._engine.load_report()


class _Slot:
    __slots__ = ("req", "off", "last", "seq", "resume")

    def __init__(self):
        self.req: Optional[Request] = None
        self.off = 0      # prefill-source tokens consumed
        self.last = 0     # last sampled token (decode feed)
        # the slot's prefill source: the request's prompt, or — for a
        # request resuming after preemption — prompt + committed tokens
        # minus the last one (the rows whose KV must be resident before
        # decode continues; the last committed token is the decode feed)
        self.seq = None
        # resume=True: the final prefill chunk's sample must NOT commit
        # (it would re-predict an already-committed token); decode
        # restarts from the preset ``last`` instead
        self.resume = False


class _Session:
    """One retained multi-turn KV session (``submit(session=)``).

    After a turn finishes, the engine keeps the request's page chain
    alive here (the session holds the refs a slot normally drops at
    release): ``tokens`` is the full conversation so far (prompt +
    generated), ``pages`` its page chain, and ``kv_len`` the rows of
    that chain holding token-exact KV of ``tokens[:kv_len]`` — a
    returning turn whose prompt extends the conversation resumes from
    that tail instead of re-prefilling the history.  ``digests`` are
    the crc32 chain digests of the full retained pages (same form as
    ``paged.page_digests``), published through ``/load`` so the fleet
    router's cache-affinity scoring lands returning turns here.

    ``busy``/``owner``: while a resumed turn is in flight the refs
    live on its slot (``pages`` is empty) and only that owner's finish
    installs the session's next state — a concurrently forked
    regeneration (same session key while busy) serves independently
    off the prefix cache and never clobbers the owner's install."""

    __slots__ = ("sid", "tokens", "pages", "kv_len", "digests",
                 "last_used", "busy", "owner")

    def __init__(self, sid):
        self.sid = sid
        self.tokens = np.zeros(0, np.int32)
        self.pages: List[int] = []
        self.kv_len = 0
        self.digests: List[int] = []
        self.last_used = time.perf_counter()
        self.busy = False
        self.owner: Optional[int] = None   # owning request's rid


class ServingEngine:
    """Slot-based continuous batching over one compiled decode tick.

    Args:
      model: a ``GPTForCausalLM``-shaped model (``.gpt`` backbone with
        ``caches``/``cache_pos`` support, tied LM head).  A weight-only
        quantized model (``load_for_serving`` of a ``quant=`` artifact)
        serves through the same tick programs — its projections route to
        the fused dequant GEMM inside the jitted tick, halving the
        weight bytes every decode step streams (docs/SERVING.md,
        "Weight-only quantized serving").
      max_slots: concurrent request capacity (the static batch B).
      max_len: per-slot KV capacity; a request needs
        ``len(prompt) + max_new_tokens <= max_len - max(chunk, spec_k+1)``
        (headroom for the widest in-flight cache write).
      chunk: prefill chunk width per tick (decode uses 1 of it).
      temperature/top_k/top_p: engine-default sampling config (0.0 =
        greedy, matching ``model.generate(temperature=0.0)``
        token-for-token); :meth:`submit` may override per request.
      eos_token_id: optional early-stop token.
      spec_k: >0 enables speculative decoding — on all-decode ticks a
        drafter proposes up to ``spec_k`` tokens per slot and ONE fused
        verify program scores all ``spec_k+1`` positions, committing the
        longest prefix matching the target's greedy argmax (exact greedy
        equivalence; slots sampling at temperature>0 simply draft 0 and
        advance 1 token/tick).  Prefilling slots keep the chunk-wide
        program unchanged.  Acceptance counters land in ``stats``
        (``spec_ticks``/``spec_drafted``/``spec_accepted``).
      drafter: 'ngram' (model-free prompt-lookup, default), a small
        ``GPTForCausalLM`` draft model, or any object speaking the
        ``nn.decode`` drafter interface.
      cache_mode: "dense" (the historical per-slot ``max_slots x
        max_len`` regions) or "paged" — a global page pool
        (``num_pages x page_size`` KV rows per layer) with per-slot page
        tables.  Paged admission reserves each request's ACTUAL page
        footprint (``prompt + max_new`` plus the write-window reserve,
        in pages) instead of a whole ``max_len`` slot, so short requests
        stop stranding HBM and more streams fit the same pool
        (``inference/paged.py``; attention gathers through the table via
        ``incubate/nn/kernels/paged_attention.py`` — the Pallas decode
        kernel on TPU, a token-exact jnp reference elsewhere).
      page_size: KV rows per page (paged mode).  16 balances internal
        fragmentation (~page_size/2 rows wasted per request) against
        page-table width; keep it a multiple of 8 so the decode kernel
        engages (sublane alignment).
      num_pages: pool size INCLUDING the reserved null page 0.  Default
        ``max_slots * ceil(max_len/page_size) + 1`` (the dense worst
        case); size it down to your HBM budget — admission simply queues
        requests whose footprint doesn't fit yet.
      prefix_cache: keep finished prompts' full pages in a radix cache
        so a later request sharing a page-aligned prompt prefix (e.g. a
        system prompt) maps the same physical pages and prefills only
        its suffix (copy-on-write by recompute: the shared tail page is
        re-prefilled privately, so shared pages are never written).
      slo_window_s: span of the rolling TTFT/TPOT/e2e/queue-wait
        percentile windows :meth:`load_report` (and the ``/load``
        endpoint) publishes — "p99 over the last N seconds", the signal
        a least-loaded router dispatches on (docs/OBSERVABILITY.md,
        "SLO telemetry and the /load report").
      session_ttl_s: idle lifetime of a retained multi-turn session
        (``submit(session=)``); ``None`` (default) disables the TTL
        sweep — sessions then live until LRU/admission-pressure
        eviction, :meth:`drain`, or :meth:`drop_sessions`.
      max_sessions: LRU cap on retained sessions (docs/SERVING.md,
        "Multi-turn sessions").
      priority_aging_s: seconds of queue wait that promote a request
        one priority class (batch → default → interactive) — the
        anti-starvation guarantee under sustained higher-priority
        load; ``None`` disables aging (strict class order).
      prefill_budget: per-tick PREFILL token budget across slots
        (chunked-prefill fairness): prefill chunks are granted in
        priority order up to this many tokens per tick, the rest
        defer — a long batch prompt then interleaves with decode
        ticks instead of monopolizing every tick's width.  ``None``
        (default) = unbounded, the historical behavior.
      preempt: allow admission pressure to preempt a strictly
        lower-priority in-flight stream (release its pages, re-queue
        it; re-admission replays the committed tokens through the
        prefix/session cache — token-exact for greedy requests).
        Disabled automatically while draining and under pp.
      preempt_limit: max preemptions of one request (thrash bound);
        past it the request is never picked as a victim again.
        docs/SERVING.md, "Priority and preemption".
    """

    # bounded count of radix-cache chain digests the /load report's
    # prefix_digest block carries (class attr so a deployment with a
    # huge shared-prefix population can widen it)
    PREFIX_DIGEST_LIMIT = 64

    def __init__(self, model, max_slots=8, max_len=512, chunk=16,
                 temperature=0.0, top_k=None, eos_token_id=None,
                 auto_run=True, decode_window=8, top_p=None, spec_k=0,
                 drafter="ngram", cache_mode="dense", page_size=16,
                 num_pages=None, prefix_cache=True, slo_window_s=60.0,
                 session_ttl_s=None, max_sessions=64,
                 priority_aging_s=30.0, prefill_budget=None,
                 preempt=True, preempt_limit=2):
        import jax
        import jax.numpy as jnp

        model.eval()
        self.model = model
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.chunk = int(chunk)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.eos_token_id = eos_token_id
        self.auto_run = bool(auto_run)
        self._decode_window = max(1, min(int(decode_window), self.chunk))
        self.spec_k = int(spec_k)
        self._aging_s = (None if priority_aging_s is None
                         else float(priority_aging_s))
        # >= 1 so the highest-priority prefilling slot always makes
        # progress — a zero budget would stall every prefill forever
        self._prefill_budget = (None if prefill_budget is None
                                else max(1, int(prefill_budget)))
        self._preempt = bool(preempt)
        self._preempt_limit = max(0, int(preempt_limit))

        cfg = model.config
        self._head_dim = cfg.hidden_size // cfg.num_heads
        self._dtype = model.gpt.wte.weight._value.dtype

        params, bufs = model.functional_state()
        # the head ties wte, so the backbone owns every parameter
        self._params = {k[len("gpt."):]: v for k, v in params.items()
                        if k.startswith("gpt.")}
        self._bufs = {k[len("gpt."):]: v for k, v in bufs.items()
                      if k.startswith("gpt.")}
        self._mesh = model._param_mesh()
        self._pp = 1
        amb = self._ambient_pp_mesh()
        if amb is not None:
            self._mesh = amb
            self._pp = amb.shape["pp"]

        self._lock = make_lock("serving.engine")
        self._pending = collections.deque()
        # graceful-removal flag (drain()): submit refuses, queued +
        # inflight requests run to completion, then the loop idles out
        self._draining = False
        # terminal loop-crash record (the fail-all path stamps it): a
        # drain() in progress must report the crash — the backlog was
        # FAILED, not completed — instead of reading the emptied
        # slots/queue as a clean drain
        self._crashed = None
        # per-token streaming hand-off buffer: (req, token|None) pairs
        # appended under the engine lock by the commit/abort paths and
        # delivered by _flush_streams on the driver thread AFTER the
        # lock is released (a blocking on_token — bounded-queue
        # backpressure — must stall only the decode loop)
        self._stream_emit = []
        # bounded terminal-abort ring for /debug/requests: aborted
        # requests leave the slot table immediately, so the debug
        # surface needs its own short memory of WHERE they died
        self._recent_aborts = collections.deque(maxlen=32)
        # count of queued requests carrying a submit(deadline_s=): the
        # per-tick expiry sweep is gated on this, so the common
        # no-deadline case pays one int check, not an O(queue) scan
        self._deadline_queued = 0
        self._slots = [_Slot() for _ in range(self.max_slots)]
        self._lengths = np.zeros(self.max_slots, np.int32)
        self._inflight = {}  # wave -> (consumed, finishing, reqs) at entry
        self._running = False
        self._loop_thread = None
        self._tickno = 0
        # device-resident per-tick constants, rebuilt only when slot
        # membership / page tables change (tick-dispatch trim): a
        # steady-state decode tick then issues ONE program dispatch plus
        # the designed token fetch — no per-tick host->device staging of
        # unchanged sampling vectors or page tables
        self._sampling_cache = None
        self._sampling_dev = None
        self._pt_dev = None
        # MoE serving: per-token routing runs INSIDE the jitted tick (the
        # MoELayer MLP is cache-independent, so the dense/paged programs
        # need no structural change); arming collect_router_stats makes
        # each tick additionally return (mean router entropy, per-expert
        # load) which ride the tick's single designed fetch into the
        # moe_router_entropy / moe_expert_load histograms.  Eval routing
        # is DROPLESS (parallel/moe.py), so a token's output never
        # depends on which other slots share its tick batch — the
        # engine's token-exactness contract vs generate() holds for MoE.
        from ..parallel.moe import MoELayer as _MoELayer
        moe_layers = [l for l in model.sublayers(include_self=True)
                      if isinstance(l, _MoELayer)]
        self._moe = bool(moe_layers) and self._pp == 1
        self._moe_num_experts = (moe_layers[0].num_experts
                                 if moe_layers else 0)
        if self._moe:
            # armed for the MODEL's lifetime, deliberately: the flag is
            # read at trace time, so disarming on shutdown would break a
            # second live engine's next lazily-built tick flavor (it
            # expects the 3-output trace).  Cost to non-engine users of
            # the same model is nil where it matters — a jitted
            # generate() never consumes the stats, so XLA dead-code
            # eliminates them from the compiled program; only fully
            # eager forwards pay the per-layer entropy/load arithmetic.
            for l in moe_layers:
                l.collect_router_stats = True
        self._slo_window_s = float(slo_window_s)
        # weight-only quantized serving flag for the /load mode block —
        # by class NAME so the (Pallas-importing) quant module stays off
        # the unquantized engine's import path
        self._quantized = any(
            type(l).__name__ == "WeightOnlyLinear"
            for l in model.sublayers(include_self=True))
        self._init_metrics()
        # per-replica fault point name, precomputed (probed every tick)
        self._tick_fault_point = f"serving.tick[{self._engine_id}]"
        self._key = jax.random.key(0)

        self._spec = None
        if self.spec_k > 0 and self._pp > 1:
            import warnings
            warnings.warn("spec_k is not supported on the pipeline-"
                          "parallel tick yet; serving without "
                          "speculative decoding", stacklevel=2)
            self.spec_k = 0
        if self.spec_k > 0:
            from ..nn.decode import get_drafter
            self._spec = get_drafter(drafter, self.spec_k)
            self._spec.begin(self.max_slots, self.max_len)

        if cache_mode not in ("dense", "paged"):
            raise ValueError(f"cache_mode must be 'dense' or 'paged', "
                             f"got {cache_mode!r}")
        if cache_mode == "paged" and self._pp > 1:
            import warnings
            warnings.warn("cache_mode='paged' is not supported on the "
                          "pipeline-parallel tick yet; serving dense",
                          stacklevel=2)
            cache_mode = "dense"
        self.cache_mode = cache_mode
        self._paged = cache_mode == "paged"
        self._pool = self._prefix = None
        self._peak_occupancy = 0
        # multi-turn KV sessions (submit(session=)): sid -> _Session.
        # Works in dense mode too (conversation tokens + fleet
        # stickiness; only paged mode retains KV pages to resume from)
        self._sessions = {}
        self._session_ttl_s = (None if session_ttl_s is None
                               else float(session_ttl_s))
        self._max_sessions = int(max_sessions)
        # page-pool defrag/compaction: while a compaction's device copy
        # is in flight (driver thread, unlocked), admission must not
        # hand out pages the move plan treats as free
        self._defrag_busy = False
        self._defrag_fn = None
        if self._paged:
            from .paged import PagePool, PrefixCache
            self._page_size = int(page_size)
            if self._page_size < 1:
                raise ValueError("page_size must be >= 1")
            self._pages_per_slot = -(-self.max_len // self._page_size)
            if num_pages is None:
                num_pages = self.max_slots * self._pages_per_slot + 1
            self._pool = PagePool(int(num_pages), self._page_size)
            if prefix_cache:
                self._prefix = PrefixCache(self._pool)
            self._page_tables = np.zeros(
                (self.max_slots, self._pages_per_slot), np.int32)
            self._slot_pages = [[] for _ in range(self.max_slots)]
            self._g_pages_free.set(self._pool.free)
            self._defrag_fn = self._build_defrag_fn()

        if self._pp > 1:
            self._build_pp_tick()
        else:
            self._build_tick()
        self._alloc_caches(jnp)
        # declare this engine shared for the race sanitizer (zero cost
        # when off — returns self untouched).  atomic: _tickno is read
        # lock-free by its only writer, the driver thread (the same
        # single-aligned-read contract the `# pht-lint: gil-atomic`
        # annotations on the _run_tick* read sites claim statically).
        # _caches/_sampling_dev/_pt_dev/_xbuf are DRIVER-OWNED device
        # staging: touched lock-free on the tick path by design
        # (staging under _lock would be PHT003 lock-across-dispatch)
        # and invalidated under the lock by admission/release — safe
        # because the single-driver guard serializes every driver, and
        # driver handoff (loop exit -> next burst's fresh loop thread,
        # or sync step()) happens through _lock/_running; the Eraser
        # model only tolerates ONE silent owner handoff, and fleet
        # traffic restarts the loop thread per burst, so these are
        # declared rather than false-flagged on the third driver.
        share_object(self, f"serving.engine[{self._engine_id}]",
                     atomic=("_tickno", "_caches", "_sampling_dev",
                             "_pt_dev", "_xbuf"))

    # ------------------------------------------------------------------
    def _init_metrics(self):
        """Register this engine's telemetry series (metric catalog:
        docs/OBSERVABILITY.md).  One ``engine`` label per instance keeps
        concurrently-live engines (tests, A/B deploys) from mixing
        series; ``self.stats`` stays the historical dict-shaped view."""
        reg = self._registry = _obs.get_registry()
        self._engine_id = f"e{next(_ENGINE_IDS)}"
        lbl = {"engine": self._engine_id}
        counters = {
            "ticks": reg.counter(
                "serving_ticks_total", "engine ticks run"),
            "tokens": reg.counter(
                "serving_tokens_total", "generated tokens committed"),
            "requests": reg.counter(
                "serving_requests_total", "requests submitted"),
            "spec_ticks": reg.counter(
                "serving_spec_ticks_total", "speculative verify ticks"),
            "spec_drafted": reg.counter(
                "serving_spec_drafted_total",
                "draft tokens proposed (capped at request budget)"),
            "spec_accepted": reg.counter(
                "serving_spec_accepted_total",
                "draft tokens accepted AND committed"),
            "prefix_hit_tokens": reg.counter(
                "serving_prefix_hit_tokens_total",
                "prompt tokens served from cached prefix pages "
                "(re-prefill skipped; paged cache mode only)"),
            "prompt_tokens": reg.counter(
                "serving_prompt_tokens_total",
                "prompt tokens of admitted requests (all cache modes)"),
            # goodput pair: generated tokens that reached a COMPLETED
            # request vs tokens burned on requests the engine failed
            # (loop crash fail-all) — completed/(completed+aborted) is
            # the /load report's goodput ratio
            "completed_tokens": reg.counter(
                "serving_completed_tokens_total",
                "generated tokens of requests that finished"),
            "aborted_tokens": reg.counter(
                "serving_aborted_tokens_total",
                "generated tokens of requests that failed/aborted "
                "(work the caller never got)"),
            # multi-turn sessions (submit(session=)): resumes and the
            # history tokens those resumes served straight from retained
            # pages — the turn-N TTFT win the serving_chat bench gates
            "session_resumes": reg.counter(
                "serving_session_resumes_total",
                "turns resumed from a retained session's KV pages"),
            "session_hit_tokens": reg.counter(
                "serving_session_hit_tokens_total",
                "prompt tokens served from retained session pages "
                "(re-prefill skipped; paged cache mode only)"),
            "sessions_evicted": reg.counter(
                "serving_sessions_evicted_total",
                "retained sessions evicted (TTL/LRU/admission "
                "pressure/drain/drop)"),
            "defrag_total": reg.counter(
                "serving_defrag_total",
                "KV page-pool compactions run"),
            "defrag_pages_moved": reg.counter(
                "serving_defrag_pages_moved_total",
                "KV pages relocated by pool compactions"),
            # SLO scheduler (submit(priority=)): preempted streams are
            # RE-QUEUED, not aborted — their committed tokens replay on
            # resume, so goodput (completed vs aborted) must not move
            "preemptions": reg.counter(
                "serving_preemptions_total",
                "in-flight streams preempted by higher-priority "
                "admission (pages released/demoted, request re-queued)"),
            "preempt_replay_tokens": reg.counter(
                "serving_preempt_replay_tokens_total",
                "committed rows re-prefilled when a preempted stream "
                "resumed (rows the prefix/session cache did not cover "
                "— the preemption cost the cache could not absorb)"),
        }
        self._c = {k: fam.labels(**lbl) for k, fam in counters.items()}
        self.stats = _EngineStats(self._c)
        self._h_ttft = reg.histogram(
            "serving_ttft_seconds",
            "submit to first generated token", unit="s").labels(**lbl)
        self._h_tpot = reg.histogram(
            "serving_tpot_seconds",
            "mean inter-token latency past the first token",
            unit="s").labels(**lbl)
        self._h_e2e = reg.histogram(
            "serving_e2e_seconds",
            "submit to request completion", unit="s").labels(**lbl)
        tick_fam = reg.histogram(
            "serving_tick_seconds",
            "device tick wall time by program flavor", unit="s")
        self._h_tick = {f: tick_fam.labels(flavor=f, **lbl)
                        for f in ("prefill", "decode", "spec", "pp")}
        self._h_accept = reg.histogram(
            "serving_spec_accept_ratio",
            "per-spec-tick accepted/drafted ratio",
            buckets=_obs.RATIO_BUCKETS).labels(**lbl)
        self._g_occupancy = reg.gauge(
            "serving_batch_occupancy",
            "slots holding an active request this tick").labels(**lbl)
        self._g_queue = reg.gauge(
            "serving_queue_depth", "requests waiting for a slot").labels(**lbl)
        # per-priority-class queue depth: a shallow TOTAL queue can hide
        # an interactive queue starving behind a deep batch queue — the
        # router's least-loaded scoring needs the split (three bounded
        # children per engine, not a per-request series)
        cls_fam = reg.gauge(
            "serving_class_queue_depth",
            "queued requests per priority class")
        self._g_class_queue = {
            c: cls_fam.labels(**{"class": c}, **lbl) for c in PRIORITY_RANK}
        # achieved weight HBM: every param/buffer array the tick programs
        # stream per token (int8 quantization should read ~half the bf16
        # bytes — the serving_int8 bench row embeds this as evidence).
        # .nbytes is shape math on the jax Array, not a transfer.
        self._g_weight_bytes = reg.gauge(
            "serving_weight_bytes",
            "model weight bytes resident for the decode tick "
            "(params + quant scales + buffers)").labels(**lbl)
        self._g_weight_bytes.set(
            sum(int(v.nbytes) for v in self._params.values())
            + sum(int(v.nbytes) for v in self._bufs.values()))
        # paged-KV pool gauges (stay 0 in dense mode): admission headroom
        # and the leak tripwire tools/perf_gate.py reads off the bench row
        self._g_pages_used = reg.gauge(
            "serving_kv_pages_in_use",
            "KV pool pages currently allocated").labels(**lbl)
        self._g_pages_free = reg.gauge(
            "serving_kv_pages_free",
            "KV pool pages on the free list").labels(**lbl)
        # multi-turn session retention (docs/SERVING.md): how many
        # conversations this replica holds warm, and the pages they pin
        # (distinct — sessions can share prompt pages via the cache)
        self._g_sessions = reg.gauge(
            "serving_sessions_retained",
            "multi-turn KV sessions currently retained").labels(**lbl)
        self._g_session_pages = reg.gauge(
            "serving_session_pages_retained",
            "distinct KV pages pinned by retained sessions").labels(**lbl)
        # MoE router telemetry (registered only for MoE engines so dense
        # engines don't grow empty series): entropy distribution + one
        # per-expert load-share histogram — a hot expert shows up as its
        # series' mass moving right while the others move left
        self._h_moe_ent = None
        self._h_moe_load = ()
        if self._moe:
            self._h_moe_ent = reg.histogram(
                "moe_router_entropy",
                "mean per-token router entropy per MoE decode tick "
                "(nats; ln(num_experts) = uniform routing)").labels(**lbl)
            load_fam = reg.histogram(
                "moe_expert_load",
                "per-tick fraction of kept (dispatched) token slots "
                "routed to each expert", buckets=_obs.RATIO_BUCKETS)
            self._h_moe_load = tuple(
                load_fam.labels(expert=str(e), **lbl)
                for e in range(self._moe_num_experts))
        # event-level observability: always-on flight ring (request
        # lifecycle marks + tick summaries feed the crash post-mortem)
        # and the /debug/requests slot table (weakly registered — a
        # dropped engine vanishes from the endpoint)
        self._flight = _flight.get_flight_recorder()
        _tr.register_introspection_source(self._engine_id, self)
        # rolling SLO windows (NOT registry families: per-engine working
        # state, no labels, exact "last N seconds" semantics the
        # lifetime histograms cannot give) — the percentile source for
        # load_report()/the /load endpoint.  queue_wait feeds at admit,
        # ttft at first token, tpot per decode tick, e2e at finish.
        self._slo = {k: _obs.SlidingWindowHistogram(
            window_s=self._slo_window_s)
            for k in ("ttft", "tpot", "e2e", "queue_wait")}
        # per-priority-class ttft/queue-wait windows: the control signal
        # the SLO scheduler is judged by ("interactive ttft p99 under
        # mixed load"), published via /load's slo.classes block — 3x2
        # bounded windows, same exact last-N-seconds semantics
        self._slo_cls = {c: {k: _obs.SlidingWindowHistogram(
            window_s=self._slo_window_s) for k in ("ttft", "queue_wait")}
            for c in PRIORITY_RANK}
        # /load registration: the engine IS its own load source, and the
        # same report rides /debug/requests under "<eid>.load" via a
        # strongly-held adapter (both registries are weak — a dropped
        # engine vanishes from the endpoints without unregister)
        _tr.register_load_source(self._engine_id, self)
        self._load_debug = _LoadDebugSource(self)
        _tr.register_introspection_source(f"{self._engine_id}.load",
                                          self._load_debug)

    @property
    def engine_id(self) -> str:
        """Stable per-process replica name (``e<N>``): the label on this
        engine's metric series, its ``/load`` + ``/debug/requests``
        registrations, its liveness beacon (``serving.<id>``) and its
        per-replica fault point (``serving.tick[<id>]``) — the handle a
        fleet router addresses this replica by."""
        return self._engine_id

    @property
    def draining(self) -> bool:
        return self._draining

    # ------------------------------------------------------------------
    @staticmethod
    def _ambient_pp_mesh():
        from ..parallel.api import get_mesh
        m = get_mesh()
        if m is not None and m.shape.get("pp", 1) > 1:
            return m
        return None

    def _alloc_caches(self, jnp):
        import jax
        cfg = self.model.config
        if self._paged:
            # one global page pool per layer: pages are slot-agnostic, so
            # there is no batch dim to shard — heads ride 'mp' (the qkv
            # projection's natural output sharding), pages replicate over
            # the data axes (parallel/api.py page_pool_sharding)
            shape = (self._pool.num_pages, self._page_size,
                     cfg.num_heads, self._head_dim)
            sh = None
            if self._mesh is not None:
                from ..parallel.api import page_pool_sharding
                sh = page_pool_sharding(self._mesh)
            put = (lambda a: jax.device_put(a, sh)) if sh is not None \
                else (lambda a: a)
            self._caches = [(put(jnp.zeros(shape, self._dtype)),
                             put(jnp.zeros(shape, self._dtype)))
                            for _ in range(cfg.num_layers)]
            return
        B, L = self.max_slots, self.max_len
        shape = (B, L, cfg.num_heads, self._head_dim)
        if self._pp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            zeros = jnp.zeros((cfg.num_layers,) + shape, self._dtype)
            sh = NamedSharding(self._mesh, P("pp"))
            self._caches = (jax.device_put(zeros, sh),
                            jax.device_put(zeros, sh))
            return
        sh = None
        if self._mesh is not None:
            from ..parallel.api import decode_cache_sharding
            sh = decode_cache_sharding(self._mesh)
        mk = lambda: jnp.zeros(shape, self._dtype)  # noqa: E731
        put = (lambda a: jax.device_put(a, sh)) if sh is not None else \
            (lambda a: a)
        self._caches = [(put(mk()), put(mk()))
                        for _ in range(cfg.num_layers)]

    # ------------------------------------------------------------------
    def _build_tick(self):
        """Single/mp-sharded tick: one fused program = embed + blocks
        with per-slot cache writes + last-valid gather + head + sample.

        Two program widths are kept (jit cache by token-chunk width):
        the chunk-wide program runs only on ticks where some slot is
        prefilling; steady-state decode ticks run the width-1 program —
        otherwise every decode tick would compute ``chunk`` columns for
        one valid token (measured 3.2k vs 12.2k device tok/s at b8)."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..nn.layer import functional_call
        from ..parallel.moe import collect_router_stats as _moe_stats

        model = self.model
        bufs = self._bufs
        moe = self._moe

        def mk_tick(sample):
            # pt=None compiles the dense trace; the paged engine passes
            # its (B, pages_per_slot) page table every tick (host numpy —
            # tiny — so admission/free only ever touch host state)
            def tick(params, caches, tokens, starts, nvalid, temps, topks,
                     topps, key, tickno, pt=None):
                hidden, caches = functional_call(
                    model.gpt, params, (Tensor(tokens),),
                    kwargs={"caches": caches, "cache_pos": starts,
                            "page_table": pt},
                    buffers=bufs, training=False)
                last = jnp.take_along_axis(
                    hidden, (nvalid - 1).astype(jnp.int32)[:, None, None],
                    axis=1)[:, 0]  # (B, h): each slot's last valid position
                logits = last @ params["wte.weight"].T
                # path tag 0: the single-step and multi-step programs must
                # draw from disjoint PRNG domains (tickno vs tickno*M+t
                # counters would otherwise collide for temperature>0)
                nxt = sample(
                    logits, temps, topks, topps,
                    jax.random.fold_in(jax.random.fold_in(key, 0), tickno))
                toks = nxt[:, 0].astype(jnp.int32)
                if moe:
                    # router stats left on the layers by the forward just
                    # traced — returned as program outputs so they ride
                    # the tick's single designed fetch
                    return caches, toks, _moe_stats(model.gpt)
                return caches, toks
            return sanitize_donation(jax.jit(tick, donate_argnums=(1,)),
                                     donate_argnums=(1,),
                                     site="serving.tick")

        self._tick, self._tick_mk = {}, mk_tick

        # multi-step decode window: when NO slot is prefilling, one tick
        # runs M in-program decode steps (lax.fori_loop with in-jit
        # sampling feedback), amortizing per-tick program overheads the
        # way generate()'s fused loop does — scheduling granularity drops
        # to M ticks, a standard serving trade (single-step: 7.1k device
        # tok/s at b8; window=8: 9.1k; the fused loop: 12.2k)
        M = self._decode_window

        E = self._moe_num_experts

        def mk_tick_multi(sample):
            def tick_multi(params, caches, last_tok, starts, temps, topks,
                           topps, key, tickno, pt=None):
                B = last_tok.shape[0]
                outbuf = jnp.zeros((B, M), jnp.int32)

                def body(t, carry):
                    if moe:
                        caches, cur, outbuf, acc = carry
                    else:
                        caches, cur, outbuf = carry
                    hidden, caches = functional_call(
                        model.gpt, params, (Tensor(cur[:, None]),),
                        kwargs={"caches": caches,
                                "cache_pos": starts + t.astype(jnp.int32),
                                "page_table": pt},
                        buffers=bufs, training=False)
                    logits = hidden[:, 0] @ params["wte.weight"].T
                    nxt = sample(
                        logits, temps, topks, topps,
                        jax.random.fold_in(jax.random.fold_in(key, 1),
                                           tickno * M + t)
                    )[:, 0].astype(jnp.int32)
                    outbuf = jax.lax.dynamic_update_slice(
                        outbuf, nxt[:, None],
                        (jnp.zeros((), jnp.int32), t.astype(jnp.int32)))
                    if moe:
                        # accumulate the in-loop steps' router stats in
                        # the carry (the side-channel values are local to
                        # each body trace; only the carry survives)
                        e, l = _moe_stats(model.gpt)
                        return caches, nxt, outbuf, (acc[0] + e, acc[1] + l)
                    return caches, nxt, outbuf

                if moe:
                    # per-token accumulators (B rows, width 1 per step):
                    # the engine masks inactive slots after the fetch
                    zero = (jnp.zeros((B,), jnp.float32),
                            jnp.zeros((B, E), jnp.float32))
                    caches, _, outbuf, acc = jax.lax.fori_loop(
                        0, M, body, (caches, last_tok, outbuf, zero))
                    return caches, outbuf, (acc[0] / M, acc[1] / M)
                caches, _, outbuf = jax.lax.fori_loop(
                    0, M, body, (caches, last_tok, outbuf))
                return caches, outbuf
            return sanitize_donation(
                jax.jit(tick_multi, donate_argnums=(1,)),
                donate_argnums=(1,), site="serving.tick_multi")

        self._tick_multi, self._tick_multi_mk = {}, mk_tick_multi

        if self.spec_k > 0:
            self._build_spec_tick()

    def _mk_sampler(self, skey):
        """The per-tick sampling closure, in static flavors compiled as
        separate programs.  ``skey=False`` bakes the engine-global scalar
        config (the historical single-argmax/top-k trace — no per-row
        sort/nucleus work on the hot path).  ``skey=(tk_on, tp_on)``
        routes the per-slot override vectors through ``_sample``'s vector
        mode, with the top-k sort and the nucleus softmax/cumsum each
        compiled in only when some row actually enables that filter.
        ``_sampling_vectors`` picks the flavor per tick, so engines whose
        requests never override sampling never even compile a vector
        variant."""
        model = self.model
        if skey is False:
            t, k, p = self.temperature, self.top_k, self.top_p

            def sample(logits, temps, topks, topps, key):
                return model._sample(logits, t, k, top_p=p, key=key)
            return sample
        tk_on, tp_on = skey

        def sample(logits, temps, topks, topps, key):
            return model._sample(logits, temps,
                                 topks if tk_on else None,
                                 top_p=topps if tp_on else None, key=key)
        return sample

    def _prog(self, name, skey):
        """Build-or-reuse the jitted ``name`` program for sampler flavor
        ``skey`` (flavors compile lazily on first use).  Every program is
        wrapped by ``observability.instrument_jit`` so builds — including
        shape-keyed retraces inside one flavor, e.g. the width-1 vs
        chunk-wide tick — land in ``jit_builds_total{site=serving.*}``:
        the recompilation-regression tripwire tools/perf_gate.py gates."""
        cache = getattr(self, name)
        fn = cache.get(skey)
        if fn is None:
            fn = cache[skey] = _obs.instrument_jit(
                getattr(self, name + "_mk")(self._mk_sampler(skey)),
                site=f"serving.{name.lstrip('_')}", engine=self._engine_id)
        return fn

    def _build_spec_tick(self):
        """Fused speculative VERIFY tick: score all ``spec_k+1`` positions
        of every decoding slot in one program over the same static-cache
        ``cache_pos`` write path the chunk program uses.  Position 0
        samples per-slot (greedy slots: argmax — the committed bonus
        token); positions >=1 are the greedy references the host-side
        acceptance compares drafts against.  Rejected tails need no cache
        rollback: the engine simply advances ``_lengths`` by accepted+1,
        and the next program rewrites ``[length, length+K]`` before any
        query can attend the stale rows (kpos <= qpos masking)."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..nn.layer import functional_call
        from ..parallel.moe import collect_router_stats as _moe_stats

        model = self.model
        bufs = self._bufs
        K = self.spec_k
        moe = self._moe

        def mk_tick_spec(sample):
            def tick_spec(params, caches, tokens, starts, temps, topks,
                          topps, key, tickno, pt=None):
                B = tokens.shape[0]
                hidden, caches = functional_call(
                    model.gpt, params, (Tensor(tokens),),
                    kwargs={"caches": caches, "cache_pos": starts,
                            "page_table": pt},
                    buffers=bufs, training=False)
                logits = hidden @ params["wte.weight"].T  # (B, K+1, V)
                # position 0 is the committed bonus/sampled token — it
                # samples per slot config (path tag 3: disjoint PRNG
                # domain from the other programs); positions >= 1 exist
                # only as greedy references for acceptance (and as the
                # committed tokens of greedy slots) — one batched argmax,
                # the same scalar-greedy math generate()'s verify uses
                first = sample(
                    logits[:, 0], temps, topks, topps,
                    jax.random.fold_in(jax.random.fold_in(key, 3), tickno))
                ref = model._sample(
                    logits[:, 1:].reshape(B * K, -1), 0.0, None)
                out = jnp.concatenate([first, ref.reshape(B, K)], axis=1)
                out = out.astype(jnp.int32)
                if moe:
                    return caches, out, _moe_stats(model.gpt)
                return caches, out
            return sanitize_donation(
                jax.jit(tick_spec, donate_argnums=(1,)),
                donate_argnums=(1,), site="serving.tick_spec")

        self._tick_spec, self._tick_spec_mk = {}, mk_tick_spec

    def _sampling_vectors(self):
        """Per-slot (skey, temperature, top_k, top_p) for the tick
        programs: the engine defaults, overridden by each slot's request
        (the per-request sampling API).  ``skey`` is False when no
        active request overrides anything — the tick then runs the
        scalar-config program (the cheap argmax/top-k trace); otherwise
        it is a ``(top_k_live, top_p_live)`` pair selecting a vector-mode
        program that compiles only the filters some row enables.
        Encodings match ``_sample``'s vector mode: top_k=0 / top_p=1.0 =
        filter off.

        Cached until admission/finish changes slot membership; the
        device-side copies (:meth:`_sampling_dev3`) share the cache's
        lifetime, so steady-state ticks reuse resident arrays instead of
        paying three H2D stagings per tick (tick-dispatch trim).  This
        runs under the engine lock and is host-only — the device staging
        happens in the unlocked tick runners (PHT003: no device dispatch
        under ``_lock``)."""
        if self._sampling_cache is not None:
            return self._sampling_cache
        B = self.max_slots
        temps = np.full(B, self.temperature, np.float32)
        topks = np.full(B, 0 if self.top_k is None else int(self.top_k),
                        np.int32)
        topps = np.full(B, 1.0 if self.top_p is None else float(self.top_p),
                        np.float32)
        vec = False
        for i, slot in enumerate(self._slots):
            req = slot.req
            if req is None:
                continue
            if req.temperature is not None:
                temps[i] = req.temperature
            if req.top_k is not None:
                topks[i] = req.top_k
            if req.top_p is not None:
                topps[i] = req.top_p
            vec = vec or (req.temperature is not None
                          or req.top_k is not None
                          or req.top_p is not None)
        skey = (bool((topks != 0).any()),
                bool((topps != 1.0).any())) if vec else False
        self._sampling_cache = (skey, temps, topks, topps)
        self._sampling_dev = None
        return self._sampling_cache

    def _sampling_dev3(self, sampling):
        """Device-resident (temps, topks, topps) for the tick programs,
        staged once per membership change (called OUTSIDE the engine
        lock, from the tick runners only — single-driver contract)."""
        if self._sampling_dev is None:
            import jax
            self._sampling_dev = tuple(
                jax.device_put(v) for v in sampling[1:4])
        return self._sampling_dev

    def _pt_kw(self):
        """Extra program kwargs: the current page table (paged mode),
        staged to device only when admission/release changed it — the
        decode steady state reuses the resident copy."""
        if not self._paged:
            return {}
        # driver-owned staging, read lock-free by design: writers that
        # INVALIDATE (_pt_dev = None on admission/release/defrag) hold
        # the lock, but the restage here runs only on the single-driver
        # tick path — mirrored in share_object's atomic= declaration
        if self._pt_dev is None:  # pht-lint: gil-atomic
            import jax.numpy as jnp
            self._pt_dev = jnp.asarray(self._page_tables)  # pht-lint: gil-atomic
        return {"pt": self._pt_dev}

    # pht-lint: hot-root (MoE decode tick path — per-tick stats observe)
    def _observe_moe(self, st, mask):
        """Record a tick's router stats (host values — they rode the
        tick's designed fetch).  ``st`` is the layer-averaged PER-TOKEN
        (entropy (n,), kept-slot counts (n, E)) pair; ``mask`` (same
        row order as the tick's token batch, flattened) selects the
        rows that belong to an ACTIVE slot's real positions — inactive
        slots' scratch rows and prefill padding route garbage every
        tick, and letting them into the histograms at partial occupancy
        would fake the expert-collapse signals operators alarm on.
        No-op for dense engines/None stats."""
        if st is None or self._h_moe_ent is None:
            return
        mask = np.asarray(mask).reshape(-1)
        if not mask.any():
            return
        ent, load = st
        ent = np.asarray(ent).reshape(-1)[mask]
        load = np.asarray(load).reshape(mask.shape[0], -1)[mask]
        self._h_moe_ent.observe(float(ent.mean()))
        counts = load.sum(0)
        tot = max(float(counts.sum()), 1.0)
        for child, cnt in zip(self._h_moe_load, counts):
            child.observe(float(cnt) / tot)

    def _run_tick(self, tokens, starts, nvalid, sampling, active):
        import jax
        vec = sampling[0]
        temps_d, topks_d, topps_d = self._sampling_dev3(sampling)
        width = 1 if int(np.max(nvalid)) <= 1 else self.chunk
        # host numpy args (tokens/starts/nvalid/tickno) ride the ONE
        # jitted dispatch's H2D; the sampling vectors are already
        # resident (tick-dispatch trim)
        out = self._prog("_tick", vec)(
            self._params, self._caches, tokens[:, :width],
            starts, nvalid, temps_d, topks_d, topps_d, self._key,
            # single aligned int read by its only writer (driver thread)
            np.int32(self._tickno), **self._pt_kw())  # pht-lint: gil-atomic
        # the tick's ONE designed device->host fetch: explicit, so the
        # transfer-guard sanitizer (observability/sanitizers.py) can
        # tell it from an accidental implicit sync (MoE router stats
        # ride the same single fetch)
        if self._moe:
            self._caches, nxt, st = out
            nxt, st = jax.device_get((nxt, st))
            # valid rows: active slots' first nvalid positions (decode
            # rows are width 1; prefill rows beyond the chunk's valid
            # span are padding)
            self._observe_moe(st, active[:, None]
                              & (np.arange(width)[None, :]
                                 < nvalid[:, None]))
            return nxt
        self._caches, nxt = out
        return jax.device_get(nxt)

    def _run_tick_spec(self, tokens, starts, sampling, active=None,
                       ndraft=None):
        import jax
        import jax.numpy as jnp
        vec = sampling[0]
        temps_d, topks_d, topps_d = self._sampling_dev3(sampling)
        toks_j, starts_j = jnp.asarray(tokens), jnp.asarray(starts)
        if self._mesh is not None:
            # place the widened (B, K+1) verify block on the KV cache's
            # batch layout up front — GSPMD then needs no reshard before
            # the in-program per-slot cache writes
            from ..parallel.api import token_batch_sharding
            sh = token_batch_sharding(self._mesh)
            toks_j = jax.device_put(toks_j, sh)
            starts_j = jax.device_put(starts_j, sh)
        res = self._prog("_tick_spec", vec)(
            self._params, self._caches, toks_j, starts_j,
            temps_d, topks_d, topps_d,
            # single aligned int read by its only writer (driver thread)
            self._key, np.int32(self._tickno),  # pht-lint: gil-atomic
            **self._pt_kw())
        # designed once-per-tick fetch (see _run_tick)
        if self._moe:
            self._caches, out, st = res
            out, st = jax.device_get((out, st))
            # valid rows: active slots' bonus token + their real drafts
            # (positions past ndraft are stale draft padding)
            B, W = np.asarray(tokens).shape
            act = (np.ones(B, bool) if active is None
                   else np.asarray(active, bool))
            nd = (np.full(B, W - 1) if ndraft is None
                  else np.asarray(ndraft))
            self._observe_moe(
                st, act[:, None] & (np.arange(W)[None, :]
                                    <= nd[:, None]))
            return out
        self._caches, out = res
        return jax.device_get(out)

    # ------------------------------------------------------------------
    def _build_pp_tick(self):
        """Interleaved-wave pipelined tick (see module docstring)."""
        import functools

        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..core.tensor import Tensor
        from ..models.gpt import param_sharding_spec
        from ..nn.layer import functional_call
        from ..parallel._smap import run_shard_map
        from ..parallel.api import stack_block_params

        model = self.model
        cfg = model.config
        mesh = self._mesh
        pp = self._pp
        if self.max_slots % pp:
            raise ValueError(
                f"max_slots={self.max_slots} must divide into pp={pp} waves")
        if cfg.num_layers % pp:
            raise ValueError(
                f"num_layers={cfg.num_layers} must divide over pp={pp}")
        self._wave = Bw = self.max_slots // pp
        C = self.chunk
        max_pos = cfg.max_position_embeddings

        prefix = model.pipeline_stage_spec()["block_prefix"]
        other, stacked = stack_block_params(
            model, mesh, param_sharding_spec, prefix, cfg.num_layers)
        self._pp_other, self._pp_stacked = other, stacked

        template = model.gpt.blocks[0]
        ln_f = model.gpt.ln_f

        def stage_chunk(st, kc, vc, x, pos):
            def body(xc, inp):
                lp, k1, v1 = inp
                y, (nk, nv) = functional_call(
                    template, lp, (Tensor(xc),),
                    kwargs={"cache": (k1, v1), "cache_pos": pos},
                    training=False)
                return y, (nk, nv)
            y, (nk, nv) = jax.lax.scan(body, x, (st, kc, vc))
            return y, nk, nv

        def spmd(sample, st_local, kcache, vcache, xbuf, tokens, starts,
                 nvalid, temps, topks, topps, wave_of_stage, other_p,
                 key, tickno):
            # kcache/vcache: (L_local, B, T, H, D) — this stage's layer
            #   slab over the FULL slot batch (a stage touches only its
            #   current wave's rows each tick).
            # xbuf: (1, Bw, C, h) local — the activation ppermuted here
            #   at the END of last tick (stage 0 replaces it with the
            #   entering wave's embedding).
            stage = jax.lax.axis_index("pp")
            wave = wave_of_stage[stage]  # my wave this tick
            sl0 = (wave * Bw).astype(jnp.int32)
            tok_w = jax.lax.dynamic_slice(
                tokens, (sl0, jnp.zeros((), jnp.int32)), (Bw, C))
            st_w = jax.lax.dynamic_slice(starts, (sl0,), (Bw,))
            nv_w = jax.lax.dynamic_slice(nvalid, (sl0,), (Bw,))

            pos_idx = jnp.clip(
                st_w[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :],
                0, max_pos - 1)
            emb = (jnp.take(other_p["gpt.wte.weight"], tok_w, axis=0)
                   + jnp.take(other_p["gpt.wpe.weight"], pos_idx, axis=0))
            x = jnp.where(stage == 0, emb.astype(xbuf.dtype), xbuf[0])

            kc_w = jax.lax.dynamic_slice_in_dim(kcache, sl0, Bw, axis=1)
            vc_w = jax.lax.dynamic_slice_in_dim(vcache, sl0, Bw, axis=1)
            y, nk, nv = stage_chunk(st_local, kc_w, vc_w, x, st_w)
            kcache = jax.lax.dynamic_update_slice_in_dim(
                kcache, nk.astype(kcache.dtype), sl0, axis=1)
            vcache = jax.lax.dynamic_update_slice_in_dim(
                vcache, nv.astype(vcache.dtype), sl0, axis=1)

            # head + sample run on every stage (uniform SPMD; the
            # (Bw,h)x(h,V) head is noise next to the layer slab) but only
            # the LAST stage's — the exiting wave's — sample is real
            xn = functional_call(
                ln_f, {"weight": other_p["gpt.ln_f.weight"],
                       "bias": other_p["gpt.ln_f.bias"]},
                (Tensor(y),), training=False)
            hid = jnp.take_along_axis(
                xn, (nv_w - 1).astype(jnp.int32)[:, None, None],
                axis=1)[:, 0]
            logits = hid @ other_p["gpt.wte.weight"].T
            nxt = sample(
                logits,
                jax.lax.dynamic_slice(temps, (sl0,), (Bw,)),
                jax.lax.dynamic_slice(topks, (sl0,), (Bw,)),
                jax.lax.dynamic_slice(topps, (sl0,), (Bw,)),
                jax.random.fold_in(jax.random.fold_in(key, 2), tickno)
            )[:, 0].astype(jnp.int32)
            is_exit = stage == pp - 1
            out = jnp.zeros((pp * Bw,), jnp.int32)
            out = jax.lax.dynamic_update_slice(
                out, jnp.where(is_exit, nxt, 0), (sl0,))
            out = jax.lax.psum(
                jnp.where(is_exit, out, jnp.zeros_like(out)), "pp")
            y = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return kcache, vcache, y[None], out

        st_specs = jax.tree.map(lambda _: P("pp"), stacked)
        other_specs = jax.tree.map(lambda _: P(), other)

        def mk_tick(sample):
            spmd_s = functools.partial(spmd, sample)

            def tick(stacked_p, kc, vc, xbuf, tokens, starts, nvalid,
                     temps, topks, topps, wave_of_stage, other_p, key,
                     tickno):
                return run_shard_map(
                    spmd_s, mesh,
                    in_specs=(st_specs, P("pp"), P("pp"), P("pp"),
                              P(), P(), P(), P(), P(), P(), P(),
                              other_specs, P(), P()),
                    out_specs=(P("pp"), P("pp"), P("pp"), P()),
                    manual_axes={"pp"},
                    args=(stacked_p, kc, vc, xbuf, tokens, starts, nvalid,
                          temps, topks, topps, wave_of_stage, other_p, key,
                          tickno))
            return sanitize_donation(
                jax.jit(tick, donate_argnums=(1, 2, 3)),
                donate_argnums=(1, 2, 3), site="serving.pp_tick")

        self._pp_tick, self._pp_tick_mk = {}, mk_tick
        self._xbuf = jax.device_put(
            jnp.zeros((pp, Bw, C, cfg.hidden_size), self._dtype),
            NamedSharding(mesh, P("pp")))

    def _run_pp_tick(self, tokens, starts, nvalid, sampling):
        import jax
        import jax.numpy as jnp
        pp = self._pp
        vec = sampling[0]
        temps_d, topks_d, topps_d = self._sampling_dev3(sampling)
        # wave at stage s this tick entered stage 0 s ticks ago (tickno:
        # single aligned int read by its only writer, the driver thread)
        wave_of_stage = np.array(
            [(self._tickno - s) % pp for s in range(pp)],  # pht-lint: gil-atomic
            np.int32)
        kc, vc = self._caches
        # partial-manual shard_map (pp manual, dp/mp auto) needs the
        # ambient mesh — same contract as _run_decode_program
        from ..core.jaxcompat import set_mesh as _set_mesh
        with _set_mesh(self._mesh):
            kc, vc, self._xbuf, nxt = self._prog("_pp_tick", vec)(
                self._pp_stacked, kc, vc, self._xbuf, jnp.asarray(tokens),
                jnp.asarray(starts), jnp.asarray(nvalid),
                temps_d, topks_d, topps_d,
                jnp.asarray(wave_of_stage), self._pp_other, self._key,
                np.int32(self._tickno))  # pht-lint: gil-atomic
        self._caches = (kc, vc)
        # designed once-per-tick fetch (see _run_tick)
        return jax.device_get(nxt)

    # ------------------------------------------------------------------
    # scheduling
    def submit(self, prompt, max_new_tokens=32, temperature=None,
               top_k=None, top_p=None, deadline_s=None,
               on_token=None, session=None, priority=None,
               trace_ctx=None) -> Request:
        """Queue a request.  ``deadline_s`` bounds the request's TOTAL
        wall budget from submit: still queued past it (queue-wait is
        where overload deadlines actually die) or still decoding past
        it, the request is aborted with :class:`DeadlineExceededError`
        (``req.error``; ``req.wait()`` returns, ``result()`` raises)
        instead of finishing an answer the caller has already given up
        on — aborted work counts against
        ``serving_aborted_tokens_total``, the lifecycle record reads
        ``where="deadline"``.  ``on_token`` streams committed tokens
        per tick (see :class:`Request`).  A draining engine
        (:meth:`drain`) refuses with :class:`EngineDraining`.

        ``session`` (any hashable key) makes this turn part of a
        multi-turn KV session: when the request finishes, its page
        chain is RETAINED under the key instead of released, and a
        later submit with the same key whose prompt extends the
        conversation (prompt + generated tokens of the last turn)
        resumes decoding from the retained tail — the history's pages
        are re-mapped, not re-prefilled, so turn-N TTFT is
        page-hit-dominated.  A prompt that diverges from the retained
        conversation keeps the longest common prefix (partial tail
        pages fork copy-on-write via ``PagePool.cow``).  Sessions are
        evicted LRU/TTL and under admission pressure — retention never
        starves admission (docs/SERVING.md, "Multi-turn sessions").

        ``priority`` ("interactive" | "default" | "batch", default
        "default") sets the request's SLO class: admission picks the
        best effective class first (FIFO within a class; queue wait
        ages a request upward every ``priority_aging_s``), and under
        admission pressure a strictly lower-priority in-flight stream
        may be PREEMPTED — re-queued, not aborted; its committed
        tokens replay through the prefix/session cache on re-admission
        (docs/SERVING.md, "Priority and preemption").

        ``trace_ctx`` (optional plain dict, minted by a fleet router —
        ``{"fleet", "fleet_rid", "attempt"}``) links this replica-local
        request back to the fleet-wide one that dispatched it: stamped
        into the lifecycle record and onto the lifecycle spans so a
        merged chrome trace shows router decision → replica ticks as
        one swimlane (docs/OBSERVABILITY.md, "Fleet telemetry").  The
        dict is the future HTTP header contract — an HTTP replica shim
        passes it through unchanged."""
        req = Request(prompt, max_new_tokens, temperature=temperature,
                      top_k=top_k, top_p=top_p, deadline_s=deadline_s,
                      on_token=on_token, session=session,
                      priority=priority, trace_ctx=trace_ctx)
        need = len(req.prompt) + req.max_new_tokens
        # reserve headroom past the last committed row for the widest
        # in-flight write: a prefill chunk, or the (spec_k+1)-wide verify
        # block — without it a tail write would clamp back onto (and
        # corrupt) committed cache rows
        reserve = max(self.chunk, self.spec_k + 1)
        if need > self.max_len - reserve:
            raise ValueError(
                f"request needs {need} cache rows; capacity is "
                f"max_len-max(chunk,spec_k+1)={self.max_len - reserve}")
        if self._paged:
            # page-granular footprint, computed on the final row index
            # (pages_for): a reserve window narrower than a page can
            # still STRADDLE a page boundary, so counting reserved
            # TOKENS (max(chunk, spec_k+1)) undercounts by one page
            # exactly when the window straddles — the allocator would
            # then hand the tail write a page the table doesn't have
            from .paged import pages_for
            npages = pages_for(need, reserve, self._page_size)
            if npages > self._pool.usable:
                raise ValueError(
                    f"request needs {npages} KV pages; the pool has "
                    f"{self._pool.usable} usable pages "
                    f"(num_pages={self._pool.num_pages}, "
                    f"page_size={self._page_size})")
        max_pos = getattr(self.model.config, "max_position_embeddings", None)
        if max_pos is not None and need > max_pos:
            # past max_pos the position lookup clips to the last row —
            # silently degraded generations; refuse up front
            raise ValueError(
                f"request needs {need} positions; the model's "
                f"max_position_embeddings is {max_pos}")
        # _tid=rid puts every span of one request — lifecycle, queued,
        # and the per-tick prefill/decode/verify shares below — on ONE
        # chrome-trace lane, so a request reads as a single swimlane
        # from submit to finish (slots are reused across requests, so a
        # slot-keyed lane would interleave strangers)
        # fleet trace context rides ONLY the lifecycle spans (they carry
        # both rid and fleet_rid, which is all the cross_stack stitcher
        # needs to re-lane the per-tick spans) — the per-token hot path
        # stays untouched, so armed fleet tracing adds zero per-tick cost
        fleet_attrs = ({"fleet_rid": req.trace_ctx["fleet_rid"]}
                       if req.trace_ctx is not None
                       and req.trace_ctx.get("fleet_rid") is not None
                       else {})
        req._span_life = _tr.start_span(
            "serving.request", _tid=req.rid, rid=req.rid,
            engine=self._engine_id,
            prompt_len=len(req.prompt), max_new=req.max_new_tokens,
            **fleet_attrs)
        req._span_queue = _tr.start_span(
            "serving.request.queued", _tid=req.rid, rid=req.rid,
            engine=self._engine_id, **fleet_attrs)
        self._flight.record(
            "req", phase="submit", rid=req.rid, engine=self._engine_id,
            prompt_len=len(req.prompt), max_new=req.max_new_tokens,
            **fleet_attrs)
        with self._lock:
            draining = self._draining
            if not draining:
                self._pending.append(req)
                if req.deadline_s is not None:
                    self._deadline_queued += 1
                self._c["requests"].inc()
                self._set_queue_gauges_locked()
                if self.auto_run and not self._running:
                    # a fresh burst supersedes a PAST crash: its failed
                    # requests already surfaced their errors, and a
                    # later drain() must judge THIS backlog, not
                    # history (the pinned stale beacon keeps alerting
                    # regardless until the new burst's first tick)
                    self._crashed = None
                    self._running = True
                    t = threading.Thread(target=self._loop, daemon=True)
                    self._loop_thread = t
                    t.start()
        if draining:
            # refuse OUTSIDE the lock: close the spans just opened and
            # leave a flight mark, then raise the typed error a router
            # reads as "place elsewhere" (drain is not a failure)
            req._span_queue.end(error="EngineDraining")
            req._span_life.end(error="EngineDraining")
            self._flight.record(
                "req", phase="reject", rid=req.rid,
                engine=self._engine_id, error="EngineDraining")
            raise EngineDraining(
                f"engine {self._engine_id} is draining: admission is "
                f"closed while queued + inflight requests finish "
                f"(drain(); shutdown() completes removal)")
        return req

    def generate(self, prompt, max_new_tokens=32, timeout=None):
        """Blocking, thread-safe: many caller threads share the engine
        (the ``ZeroCopyRun``-under-lock contract, but requests BATCH
        instead of serializing)."""
        req = self.submit(prompt, max_new_tokens)
        finished = req.wait(timeout)
        if req.error is not None:
            # engine-loop failure: surface the root cause, not a timeout
            return req.result()  # raises RuntimeError from req.error
        if not finished:
            raise TimeoutError("generation did not finish in time")
        return req.result()

    def _eff_rank_locked(self, req, now):
        """Effective priority class of a waiting request: its static
        rank, promoted one class per ``priority_aging_s`` of wait since
        SUBMIT (not the last re-queue — a preempted request keeps its
        accrued age).  The anti-starvation guarantee: any batch request
        eventually reaches rank 0 and outranks every fresh interactive
        arrival (ties break FIFO)."""
        r = req._prank
        if r and self._aging_s is not None:
            r -= int((now - req._t_submit) / self._aging_s)
            if r < 0:
                r = 0
        return r

    def _next_pending_idx_locked(self, now):
        """Index of the next request admission should try: best
        effective class first, FIFO within it (queue position breaks
        ties, so an all-default workload schedules exactly like the
        historical FIFO deque)."""
        best_i, best_k = 0, None
        for i, req in enumerate(self._pending):
            k = (self._eff_rank_locked(req, now), i)
            if best_k is None or k < best_k:
                best_i, best_k = i, k
        return best_i

    def _pick_victim_locked(self, cand, now):
        """Slot to preempt so ``cand`` can admit, or None.  A victim
        must be strictly lower effective priority than the candidate
        (so a just-preempted stream can never immediately evict its
        evictor back — no livelock) and under its preemption cap.
        Among victims: lowest effective class first, then least work
        to replay (committed rows), then the highest slot index."""
        if (not self._preempt or self._draining or self._pp > 1
                or not self._preempt_limit):
            return None
        ce = self._eff_rank_locked(cand, now)
        best = None
        for i, slot in enumerate(self._slots):
            req = slot.req
            if req is None or req._preempts >= self._preempt_limit:
                continue
            ve = self._eff_rank_locked(req, now)
            if ve <= ce:
                continue
            key = (-ve, int(self._lengths[i]), -i)
            if best is None or key < best[0]:
                best = (key, i)
        return None if best is None else best[1]

    def _preempt_slot_locked(self, i, now):
        """Preempt slot ``i``'s in-flight stream: retain its committed
        KV where a cache can hold it (session install for session
        streams — the chain must survive for the PR 16 leak/dead-session
        tripwires to stay meaningful; prefix-cache donation otherwise),
        release the slot, and RE-QUEUE the request at the front of the
        queue.  Nothing terminal happens: no error, no event, no abort
        books — re-admission replays the committed tokens (slot.seq)
        and decode continues token-exact from the last committed one."""
        slot = self._slots[i]
        req = slot.req
        if self._paged:
            if req.session is not None:
                # demote to session-retained, NOT released: the session
                # keeps the chain refs, re-admission session-resumes
                self._session_install_locked(i, req)
            elif self._prefix is not None:
                # donate the committed rows' full pages keyed by their
                # token content (prompt + generated): re-admission
                # matches them back; admission pressure can still evict
                # them (cached_only), so donation never blocks anyone
                kv_len = min(int(self._lengths[i]),
                             len(req.prompt)
                             + max(0, len(req.tokens) - 1))
                if kv_len >= self._page_size:
                    seq = np.concatenate(
                        [req.prompt, np.asarray(req.tokens, np.int32)])
                    self._prefix.insert(seq[:kv_len],
                                        self._page_tables[i],
                                        kv_len // self._page_size)
            self._release_pages_locked(i)
        slot.req = None
        slot.seq = None
        slot.resume = False
        self._sampling_cache = None  # membership changed: restage
        self._lengths[i] = 0
        req._preempts += 1
        req._t_queued = now
        self._pending.appendleft(req)
        if req.deadline_s is not None:
            self._deadline_queued += 1
        self._c["preemptions"].inc()
        req.lifecycle["preemptions"] = req._preempts
        req._span_queue = _tr.start_span(
            "serving.request.queued", _tid=req.rid, rid=req.rid,
            engine=self._engine_id, preempted=True)
        self._flight.record(
            "req", phase="preempt", rid=req.rid, engine=self._engine_id,
            slot=i, tokens=len(req.tokens), preempts=req._preempts)

    def _set_queue_gauges_locked(self):
        self._g_queue.set(len(self._pending))
        counts = dict.fromkeys(PRIORITY_RANK, 0)
        for r in self._pending:
            counts[r.priority] += 1
        for c, g in self._g_class_queue.items():
            g.set(counts[c])

    def _admit(self):
        """Move pending requests into free slots — best effective
        priority class first, FIFO within a class (aging promotes
        waiters, see ``_eff_rank_locked``).  Under pp a request admits
        into any free slot (its wave is slot // wave_size); its staged
        prompt is consumed when that wave next enters stage 0.

        Paged mode additionally requires the request's PAGE footprint to
        fit the pool — a free slot alone is not capacity.  When the
        pick cannot admit (no slot, or pages short), admission may
        PREEMPT a strictly lower-priority in-flight stream
        (``_preempt_slot_locked``) and retry; otherwise it stops —
        later same-or-lower-priority requests wait behind the pick
        rather than starving it (per-class FIFO preserved).

        A re-admitted (preempted) request resumes: its slot prefills
        ``prompt + tokens[:-1]`` (``slot.seq``) with the final chunk's
        sample discarded, and decode restarts from the last committed
        token — token-exact for greedy requests.

        Returns the prefix-hit drafter replays ``[(slot, req, skip,
        lengths_snapshot, seq)]`` for the CALLER to run after releasing
        the engine lock: the replay dispatches the drafter's jitted
        ingest program, and dispatching device work under ``_lock``
        stalls every concurrent submit()/introspection call behind the
        device (pht-lint PHT003 caught this).  Deferral is safe — only
        the driver thread touches slot state, and the replay only needs
        to land before this tick's post-verify ingest, which runs later
        on this same thread."""
        if self._defrag_busy:
            # a compaction's device copy is in flight: the move plan
            # treats low free pages as copy destinations, so admission
            # must not hand them out mid-copy — requests stay queued
            # for the tick after the commit
            return []
        self._expire_queued_locked()
        self._sweep_sessions_locked()
        replays = []
        free = [i for i, s in enumerate(self._slots) if s.req is None]
        while self._pending:
            now = time.perf_counter()
            idx = self._next_pending_idx_locked(now)
            req = self._pending[idx]
            if not free:
                v = self._pick_victim_locked(req, now)
                if v is None:
                    break
                self._preempt_slot_locked(v, now)
                free.append(v)
                continue   # re-pick: the victim joined the queue
            i = min(free)
            resume = bool(req.tokens)
            seq = (np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
                if resume else req.prompt)
            skip = 0
            if self._paged:
                skip = self._paged_admit_locked(i, req, seq, resume)
                if skip is None:
                    # pool exhausted for the pick: preempt a strictly
                    # lower-priority stream to free pages and retry, or
                    # stop admitting this tick
                    v = self._pick_victim_locked(req, now)
                    if v is None:
                        break
                    self._preempt_slot_locked(v, now)
                    free.append(v)
                    continue
            free.remove(i)
            del self._pending[idx]
            slot = self._slots[i]
            slot.req = req
            if req.deadline_s is not None:
                self._deadline_queued -= 1
            self._sampling_cache = None  # membership changed: restage
            slot.seq = seq
            slot.resume = resume
            slot.off = skip   # cache hit: those rows are already KV
            # a resumed stream decodes from its last committed token
            # (never re-sampled — the final replay chunk's sample is
            # discarded, see _stage)
            slot.last = int(req.tokens[-1]) if resume else 0
            self._lengths[i] = skip
            self._c["prompt_tokens"].inc(len(seq))
            if resume:
                self._c["preempt_replay_tokens"].inc(
                    max(0, len(seq) - skip))
            if skip and self._spec is not None:
                # snapshot the committed lengths UNDER the lock: the
                # replay itself runs after release (device dispatch must
                # not hold the engine lock — PHT003), and reading
                # self._lengths there would be an unguarded read of
                # lock-guarded state (PHT009); only slot i's row is
                # consumed (other slots replay zero tokens)
                replays.append((i, req, skip, self._lengths.copy(), seq))
            queue_s = now - req._t_queued
            req.lifecycle.update(t_admit=now, queue_s=queue_s, slot=i)
            self._slo["queue_wait"].observe(queue_s)
            self._slo_cls[req.priority]["queue_wait"].observe(queue_s)
            req._span_queue.end(slot=i)
            self._flight.record(
                "req", phase="admit", rid=req.rid, engine=self._engine_id,
                slot=i, prefix_hit=skip, queue_s=round(queue_s, 6))
        return replays

    def _expire_queued_locked(self):
        """Abort queued requests past their ``submit(deadline_s=)``
        budget (runs at every ``_admit``, i.e. every tick).  The common
        case — nobody set a deadline — is one int check, no queue scan,
        no clock read (``_deadline_queued`` is maintained by submit/
        admit/expiry/fail-all)."""
        if not self._deadline_queued:
            return
        now = time.perf_counter()
        keep = collections.deque()
        for req in self._pending:
            wait_s = now - req._t_submit
            if req.deadline_s is None or wait_s <= req.deadline_s:
                keep.append(req)
                continue
            self._deadline_queued -= 1
            req.error = DeadlineExceededError(
                f"request {req.rid} queued {wait_s:.3f}s, past its "
                f"deadline_s={req.deadline_s}; aborted un-admitted")
            # goodput accounting: same books as the loop fail-all —
            # a queued abort contributes its (zero) generated tokens
            self._c["aborted_tokens"].inc(len(req.tokens))
            req.lifecycle.update(
                t_abort=now, aborted=True, tokens=len(req.tokens),
                where="deadline", error="DeadlineExceededError")
            req._span_queue.end(error="DeadlineExceededError")
            req._span_life.end(error="DeadlineExceededError")
            self._flight.record(
                "req", phase="abort", rid=req.rid,
                engine=self._engine_id, where="deadline",
                wait_s=round(wait_s, 6), error="DeadlineExceededError")
            self._record_abort_locked(req, "deadline",
                                      "DeadlineExceededError", now)
            if req.on_token is not None:
                self._stream_emit.append((req, None))
            req._event.set()
        self._pending = keep

    def _expire_slots_locked(self):
        """The decode half of the ``submit(deadline_s=)`` budget: a
        request STILL DECODING past its deadline is aborted mid-flight
        (its slot frees this tick, its generated-so-far tokens count as
        aborted work).  Queue-wait expiry alone would let a request
        that squeaked into a slot overrun its caller's timeout by the
        whole decode.  One ``is not None`` check per slot per tick when
        nobody sets deadlines; the clock is read only when some slot
        carries one."""
        now = None
        for i, slot in enumerate(self._slots):
            req = slot.req
            if req is None or req.deadline_s is None:
                continue
            if self._pp > 1:
                # consult the record of the wave that OWNS slot i: every
                # record snapshots all slots, so matching req against
                # arbitrary records would defer forever under steady
                # decode (some wave is always mid-pipeline)
                rec = self._inflight.get(i // self._wave)
                if rec is not None and rec[2][i] is req:
                    # the slot's wave is mid-pipeline: freeing it now
                    # would let admission reuse rows the in-flight wave
                    # still writes — expire when the wave exits
                    # (<= pp ticks, _commit_pp_exit skips the stale
                    # commit either way)
                    continue
            if now is None:
                now = time.perf_counter()
            if now - req._t_submit <= req.deadline_s:
                continue
            self._abort_slot_locked(
                i, req, DeadlineExceededError(
                    f"request {req.rid} ran "
                    f"{now - req._t_submit:.3f}s, past its "
                    f"deadline_s={req.deadline_s}; aborted mid-decode "
                    f"after {len(req.tokens)} tokens"),
                "deadline", now)

    def _abort_slot_locked(self, i, req, err, where, now):
        """Terminal abort of an ADMITTED request (deadline expiry): free
        the slot like :meth:`_finish`, but book the generated tokens as
        aborted work and stamp the abort terminal on the lifecycle
        record / flight ring / ``recent_aborts`` debug ring."""
        req.error = err
        if req.session is not None:
            # retain what decoded before the abort: the next turn of
            # the conversation resumes from the partial chain instead
            # of a cold re-prefill (install takes the page refs BEFORE
            # the release below resets the slot's table)
            self._session_install_locked(i, req)
        self._slots[i].req = None
        self._sampling_cache = None  # membership changed: restage
        self._lengths[i] = 0
        if self._paged:
            self._release_pages_locked(i)
        self._c["aborted_tokens"].inc(len(req.tokens))
        req.lifecycle.update(
            t_abort=now, aborted=True, tokens=len(req.tokens),
            where=where, error=type(err).__name__)
        req._span_life.end(error=type(err).__name__)
        self._flight.record(
            "req", phase="abort", rid=req.rid, engine=self._engine_id,
            slot=i, where=where, tokens=len(req.tokens),
            error=type(err).__name__)
        self._record_abort_locked(req, where, type(err).__name__, now)
        if req.on_token is not None:
            self._stream_emit.append((req, None))
        req._event.set()

    def _record_abort_locked(self, req, where, error, now):
        """One row in the bounded ``recent_aborts`` ring
        (``/debug/requests``): aborted requests vanish from the slot
        table immediately, so WHERE they died must be visible
        somewhere curl can reach."""
        self._recent_aborts.append(
            {"rid": req.rid, "where": where, "error": error,
             "tokens": len(req.tokens), "t_abort": round(now, 6)})

    def _paged_admit_locked(self, i, req, seq, resume):
        """Reserve slot ``i``'s whole page footprint up front (worst-case
        rows = prompt + max_new + the write-window reserve, in pages):
        no mid-flight exhaustion, and the
        concurrency win is intact because the footprint tracks the
        REQUEST's need, not ``max_len``.  Cached prefix pages are mapped
        shared (refcount++) and their tokens skipped from prefill.
        ``seq`` is the prefill source (``req.prompt``, or ``prompt +
        tokens[:-1]`` when ``resume`` — a preempted stream re-admitting;
        its committed rows were donated to the prefix/session cache at
        preemption, so the match below is what makes preemption cheap).
        Returns the skipped token count, or None when the pool cannot
        fit the request yet (caller leaves it queued)."""
        from .paged import NULL_PAGE, pages_for
        P = self._page_size
        reserve = max(self.chunk, self.spec_k + 1)
        total = pages_for(len(req.prompt) + req.max_new_tokens, reserve, P)
        if req.session is not None:
            # returning turn of a retained session: resume from the
            # retained page chain instead of re-prefilling the history
            # (a busy session — its owner turn still decoding — falls
            # through to normal admission: the fork serves off the
            # prefix cache and never touches the owner's pages).  A
            # preempt-resume may take back every retained row (its last
            # committed token feeds decode, so seq's final row IS
            # consumable KV — no len-1 cap needed).
            sess = self._sessions.get(req.session)
            if sess is not None and not sess.busy and sess.pages:
                n = min(sess.kv_len, len(seq) - (0 if resume else 1))
                diff = np.nonzero(sess.tokens[:n]
                                  != seq[:n])[0]
                common = int(diff[0]) if len(diff) else int(n)
                if common > 0:
                    skip = self._session_resume_locked(i, req, sess,
                                                       total, common)
                    # None: the pool cannot cover the resume right now
                    # even after eviction — keep the head queued (FIFO;
                    # normal admission needs at least as many fresh
                    # pages, so falling through could not admit either)
                    return skip
        hit = (self._prefix.match(seq, allow_full=resume)
               if self._prefix is not None else [])
        fresh_n = total - len(hit)
        short = fresh_n - self._pool.free
        if short > 0:
            # evict ONLY when eviction can actually cover the shortfall
            # (cached_only counts exactly what evict can free leaf-up
            # right now, excluding cache-only nodes pinned under a live
            # slot's tail; session-evictable pages are the non-busy
            # sessions' exclusively-held pages — retention must never
            # starve admission) — otherwise an unadmittable head would
            # flush a hot prefix cache for nothing and still not admit
            cache_ev = (self._prefix.cached_only()
                        if self._prefix is not None else 0)
            if cache_ev + self._session_evictable_pages_locked() < short:
                if hit:
                    self._pool.decref(hit)  # hand the matched refs back
                return None
            if cache_ev:
                short -= self._prefix.evict(min(short, cache_ev))
            if short > 0:
                self._evict_sessions_for_locked(short)
        fresh = self._pool.alloc(fresh_n)
        if fresh is None:
            if hit:
                self._pool.decref(hit)
            return None
        pages = hit + fresh
        self._slot_pages[i] = pages
        self._page_tables[i] = NULL_PAGE
        self._page_tables[i, :len(pages)] = pages
        self._pt_dev = None   # table changed: restage on next tick
        self._c["prefix_hit_tokens"].inc(len(hit) * P)
        self._g_pages_used.set(self._pool.allocated)
        self._g_pages_free.set(self._pool.free)
        return len(hit) * P

    def _replay_skipped_to_drafter(self, i, req, skip, lengths, seq):
        """A prefix-cache hit skips re-prefilling rows [0, skip) — but
        the drafter's mirror only ever sees what the target tick feeds
        it, so without this replay it would propose from a hole in its
        history (never *wrong* tokens — verify rejects — just a silently
        degraded acceptance rate).  Replay in chunk-wide pieces: the
        width the drafter's ingest program is already compiled for, so
        no new trace per distinct hit length.  ``seq`` is the slot's
        prefill source (prompt, or prompt + committed tokens on a
        preempt-resume — the drafter must mirror the RESUMED history,
        not just the prompt).  ``lengths`` is the
        committed-lengths snapshot ``_admit`` took under the engine
        lock (this runs after release); other slots' rows follow the
        normal ingest convention (zero tokens written past their
        committed length — scratch the draft attention never reads)."""
        C = self.chunk
        for ofs in range(0, skip, C):
            n = min(C, skip - ofs)
            buf = np.zeros((self.max_slots, C), np.int32)
            buf[i, :n] = seq[ofs:ofs + n]
            starts = lengths.copy()
            starts[i] = ofs
            nvalid = np.zeros(self.max_slots, np.int32)
            nvalid[i] = n
            self._spec.ingest(buf, starts, nvalid)

    def _release_pages_locked(self, i):
        """Drop slot ``i``'s page references (request finished/failed).
        Pages the prefix cache also references stay allocated for future
        prefix hits; everything else returns to the free list."""
        from .paged import NULL_PAGE
        pages = self._slot_pages[i]
        if pages:
            self._pool.decref(pages)
            self._slot_pages[i] = []
        self._page_tables[i] = NULL_PAGE
        self._pt_dev = None   # table changed: restage on next tick
        self._g_pages_used.set(self._pool.allocated)
        self._g_pages_free.set(self._pool.free)

    # ------------------------------------------------------------------
    # multi-turn KV sessions (submit(session=)) — docs/SERVING.md
    # pht-lint: hot-root (session resume runs on the admission tick path)
    def _session_resume_locked(self, i, req, sess, total, common):
        """Admit slot ``i`` by resuming session ``sess``: the first
        ``common`` conversation tokens' KV rows are already resident in
        the session's retained page chain, so the slot takes those
        pages over (the session's refs transfer — no incref/decref
        churn) and prefills only the suffix.  A partial tail page that
        is SHARED (prompt pages the prefix cache also references, or a
        diverged turn cutting into cache-registered history) forks
        copy-on-write via ``PagePool.cow`` — the fork's rows re-prefill
        ("copy" by recompute), so the write-window invariant (no shared
        page in ``[start, start+reserve)``) holds by construction.

        Returns the skipped token count, or ``None`` when the pool
        cannot cover the resume even after evicting LRU sessions and
        prefix-cache pages (the request stays queued; nothing was
        mutated)."""
        P = self._page_size
        kept_n = -(-common // P)          # ceil: pages holding [0, common)
        keep = sess.pages[:kept_n]
        fresh_n = total - kept_n
        tail_shared = (common % P != 0
                       and self._pool.refcount(keep[-1]) > 1)
        need_free = fresh_n + (1 if tail_shared else 0)
        short = need_free - self._pool.free
        if short > 0:
            short -= self._evict_sessions_for_locked(
                short, exclude=sess.sid)
            if short > 0:
                if (self._prefix is None
                        or self._prefix.cached_only() < short):
                    return None
                self._prefix.evict(short)
        # commit point: the allocations below cannot fail (free pages
        # verified above; one lock hold, nothing runs in between)
        extra = sess.pages[kept_n:]
        if extra:
            # rows past the common prefix are a dead branch of the
            # conversation (diverged turn): the transfer takes ALL the
            # session's refs, the unused tail goes straight back
            self._pool.decref(extra)
        pages = list(keep)
        skip = common
        if common % P:
            page, forked = self._pool.cow(pages[-1])
            pages[-1] = page
            if forked:
                # shared tail forked to a private page: its rows are
                # re-prefilled, so round the skip down to the boundary
                skip = (common // P) * P
        from .paged import NULL_PAGE
        pages += self._pool.alloc(fresh_n)
        sess.busy = True
        sess.owner = req.rid
        sess.pages = []               # refs now live on the slot
        self._slot_pages[i] = pages
        self._page_tables[i] = NULL_PAGE
        self._page_tables[i, :len(pages)] = pages
        self._pt_dev = None   # table changed: restage on next tick
        self._c["session_resumes"].inc()
        self._c["session_hit_tokens"].inc(skip)
        self._g_pages_used.set(self._pool.allocated)
        self._g_pages_free.set(self._pool.free)
        self._update_session_gauges_locked()
        self._flight.record(
            "session", phase="resume", rid=req.rid,
            engine=self._engine_id, slot=i, hit_tokens=skip,
            kept_pages=kept_n)
        return skip

    # pht-lint: hot-root (session install runs on the tick commit path)
    def _session_install_locked(self, i, req):
        """Retain the finishing/aborting request's state as its session
        (called from ``_finish``/``_abort_slot_locked`` BEFORE the slot's
        lengths are zeroed and its pages released — the install takes
        the page refs the release would drop).  Rules: a session busy
        under ANOTHER owner is left alone (a forked regeneration must
        not clobber the owner's in-flight turn); otherwise the last
        finisher wins — previously retained pages are dropped and this
        turn's chain replaces them."""
        sid = req.session
        sess = self._sessions.get(sid)
        if sess is not None and sess.busy and sess.owner != req.rid:
            return
        if sess is None:
            if len(self._sessions) >= self._max_sessions:
                self._evict_lru_session_locked()
            sess = self._sessions[sid] = _Session(sid)
        sess.tokens = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        kv_len = 0
        if self._paged:
            # committed rows holding token-exact KV: the last generated
            # token is never fed back, and lengths can overrun actual
            # commits in multi/spec modes (window/verify advance, then
            # an early finish discards the tail) — rows [0, p + N - 1)
            # are valid in ALL modes, so clamp to that
            kv_len = min(int(self._lengths[i]),
                         len(req.prompt) + max(0, len(req.tokens) - 1))
            if sess.pages:
                # last-wins: a regeneration replaces the retained chain
                self._pool.decref(sess.pages)
                sess.pages = []
            n_keep = -(-kv_len // self._page_size)
            pages = self._slot_pages[i]
            sess.pages = pages[:n_keep]
            extra = pages[n_keep:]
            if extra:
                self._pool.decref(extra)   # write-window slack pages
            self._slot_pages[i] = []       # refs transferred to session
            P = self._page_size
            crc, digs = 0, []
            for k in range(kv_len // P):
                crc = zlib.crc32(
                    sess.tokens[k * P:(k + 1) * P].tobytes(), crc)
                digs.append(crc)
            sess.digests = digs
        sess.kv_len = kv_len
        sess.busy = False
        sess.owner = None
        sess.last_used = time.perf_counter()
        self._update_session_gauges_locked()

    def _evict_session_locked(self, sid, donate=True):
        """Evict one non-busy session.  ``donate=True`` (graceful: TTL,
        drain) hands the full retained pages to the prefix cache keyed
        by their token content first, so a turn re-admitted after the
        eviction replays from the cache instead of a cold re-prefill;
        ``donate=False`` (admission pressure, leak checks) drops the
        refs outright — the pool needs the pages NOW."""
        sess = self._sessions.pop(sid)
        if sess.pages:
            if donate and self._prefix is not None:
                self._prefix.insert(sess.tokens, sess.pages,
                                    sess.kv_len // self._page_size)
            self._pool.decref(sess.pages)
            self._g_pages_used.set(self._pool.allocated)
            self._g_pages_free.set(self._pool.free)
        self._c["sessions_evicted"].inc()
        self._flight.record(
            "session", phase="evict", engine=self._engine_id,
            donated=bool(donate and sess.pages is not None))
        self._update_session_gauges_locked()

    def _evict_lru_session_locked(self):
        cands = [s for s in self._sessions.values() if not s.busy]
        if cands:
            self._evict_session_locked(
                min(cands, key=lambda s: s.last_used).sid)

    def _evict_sessions_for_locked(self, need, exclude=None):
        """Evict LRU non-busy sessions (dropping, not donating — this
        runs under admission pressure and must FREE pages) until
        ``need`` pages came free or no candidates remain; returns the
        pages actually freed."""
        freed = 0
        while freed < need:
            cands = [s for s in self._sessions.values()
                     if not s.busy and s.sid != exclude]
            if not cands:
                break
            victim = min(cands, key=lambda s: s.last_used)
            before = self._pool.free
            self._evict_session_locked(victim.sid, donate=False)
            freed += self._pool.free - before
        return freed

    def _session_evictable_pages_locked(self):
        """Pages evicting every non-busy session would free RIGHT NOW:
        pages whose ONLY reference is a session (a refcount-1 page
        belongs to exactly one holder, so no dedup) — the session half
        of the admission headroom ``/load`` publishes next to the
        prefix cache's ``cached_only``; the two are disjoint (a page
        referenced by both has refcount >= 2 and counts in neither)."""
        if not self._paged:
            return 0
        return sum(1 for s in self._sessions.values() if not s.busy
                   for p in s.pages if self._pool.refcount(p) == 1)

    def _sweep_sessions_locked(self):
        """TTL sweep (every _admit): evict non-busy sessions idle past
        ``session_ttl_s``.  One dict check when the feature is off."""
        if self._session_ttl_s is None or not self._sessions:
            return
        now = time.perf_counter()
        for sid in [sid for sid, s in self._sessions.items()
                    if not s.busy
                    and now - s.last_used > self._session_ttl_s]:
            self._evict_session_locked(sid)

    def _update_session_gauges_locked(self):
        self._g_sessions.set(len(self._sessions))
        if self._paged:
            pages = set()
            for s in self._sessions.values():
                pages.update(s.pages)
            self._g_session_pages.set(len(pages))

    def drop_sessions(self) -> int:
        """Evict every non-busy retained session WITHOUT donating to
        the prefix cache (HBM reclaim / pool-leak checks — the bench
        rows call this before asserting ``kv_pages_in_use == 0``);
        returns how many sessions were dropped."""
        with self._lock:
            n = 0
            for sid in list(self._sessions):
                if not self._sessions[sid].busy:
                    self._evict_session_locked(sid, donate=False)
                    n += 1
            return n

    # ------------------------------------------------------------------
    # on-device page defrag / compaction — docs/SERVING.md
    #
    # A long-lived pool fragments: sessions and cache nodes free pages
    # scattered across the address range, so ``allocated`` stays small
    # while ``highest_allocated`` stays large — the region the tick's
    # gather actually touches.  Compaction moves every allocated page
    # into the low end in three phases: PLAN under the lock (pool is
    # idle-checked, ``_defrag_busy`` set so _admit stays out), device
    # COPY unlocked (PHT003: never dispatch under the lock), COMMIT
    # under the lock (``apply_moves`` re-validates per pair, then the
    # prefix cache, retained sessions and page tables remap).
    def defrag(self) -> int:
        """Compact the paged KV pool (no-op in dense mode or when the
        pool is already dense-packed); returns pages moved.  Runs only
        at a quiet point — zero active slots, empty queue, no inflight
        pp waves — and respects the single-driver contract (raises if
        the auto_run loop is concurrently driving; the loop runs
        compaction itself on idle ticks, see ``_maybe_defrag``)."""
        if not self._paged:
            return 0
        with self._lock:
            if self._running and \
                    threading.current_thread() is not self._loop_thread:
                err = RuntimeError(
                    "engine is being driven by its auto_run loop; "
                    "defrag() from another thread would touch donated "
                    "caches mid-tick — the loop compacts on idle ticks "
                    "itself")
                err._pht_usage_error = True
                raise err
        return self._defrag_impl()

    # pht-lint: hot-root (auto-defrag check runs on every idle tick)
    def _maybe_defrag(self):
        """Idle-tick auto-compaction (driver thread): trigger only when
        the touched region is more than twice the live page count —
        cheap two-int predicate, so probing every idle tick is free."""
        with self._lock:
            if (self._pool is None or self._defrag_busy
                    or self._pool.highest_allocated()
                    <= 2 * self._pool.allocated):
                return 0
        return self._defrag_impl()

    def _defrag_impl(self) -> int:
        moves = None
        try:
            with self._lock:
                if self._defrag_busy:
                    return 0
                # quiet point required: a live slot's page table (or a
                # pp wave's entry-time snapshot) would go stale under a
                # move; admission is re-gated below via _defrag_busy
                if (self._pending
                        or any(s.req is not None for s in self._slots)
                        or self._inflight_live()):
                    return 0
                moves = self._pool.compaction_plan()
                if not moves:
                    return 0
                self._defrag_busy = True
            # device copy OUTSIDE the lock (PHT003) — _admit returns []
            # while _defrag_busy, so no slot can map a moving page
            self._dispatch_defrag_moves(moves)
            with self._lock:
                applied = self._pool.apply_moves(moves)
                remap = dict(applied)
                if self._prefix is not None:
                    self._prefix.remap_pages(remap)
                for sess in self._sessions.values():
                    sess.pages = [remap.get(p, p) for p in sess.pages]
                self._pt_dev = None   # tables restage from the remap
                self._c["defrag_total"].inc()
                self._c["defrag_pages_moved"].inc(len(applied))
                self._g_pages_used.set(self._pool.allocated)
                self._g_pages_free.set(self._pool.free)
                self._flight.record(
                    "defrag", phase="commit", engine=self._engine_id,
                    moved=len(applied),
                    high=self._pool.highest_allocated())
                return len(applied)
        finally:
            if moves:
                with self._lock:
                    self._defrag_busy = False

    def _build_defrag_fn(self):
        """CONSTRUCT (not trace) the compaction copy program, called
        once from ``__init__`` — construction inside the defrag path
        itself would be a per-pass retrace hazard (PHT002); the actual
        trace happens on the first executed plan."""
        import jax

        def move(caches, srcs, dsts):
            return [(k.at[dsts].set(k[srcs]),
                     v.at[dsts].set(v[srcs])) for k, v in caches]

        return _obs.instrument_jit(
            sanitize_donation(jax.jit(move, donate_argnums=(0,)),
                              donate_argnums=(0,), site="serving.defrag"),
            site="serving.defrag", engine=self._engine_id)

    def _dispatch_defrag_moves(self, moves):
        """One jitted gather-scatter per cache layer copies every
        moving page's K and V rows src→dst in a single dispatch.  The
        src/dst vectors pad to the next power of two with (0, 0) pairs
        so plans of different sizes reuse one trace: page 0 is the
        NULL page — duplicate dst-0 writes all carry page 0's own rows,
        so the no-op padding is write-write safe."""
        import jax.numpy as jnp
        n = 1
        while n < len(moves):
            n *= 2
        srcs = np.zeros(n, np.int32)
        dsts = np.zeros(n, np.int32)
        for j, (s, d) in enumerate(moves):
            srcs[j], dsts[j] = s, d
        self._caches = self._defrag_fn(
            self._caches, jnp.asarray(srcs), jnp.asarray(dsts))

    def _check_write_windows_locked(self, starts):
        """Tripwire for the paged no-shared-writes invariant: no active
        slot's write window ``[start, start+reserve)`` may map a page
        with refcount > 1 — the prefix cache's round-down-to-a-page-
        boundary match (copy-on-write by recompute) guarantees it, so a
        violation is a refcount bug; fail the tick loudly rather than
        serve KV another request (or the cache) can see corrupted."""
        from .paged import NULL_PAGE
        P = self._page_size
        reserve = max(self.chunk, self.spec_k + 1)
        for i, slot in enumerate(self._slots):
            if slot.req is None:
                continue
            lo = int(starts[i]) // P
            hi = min((int(starts[i]) + reserve - 1) // P,
                     self._pages_per_slot - 1)
            for k in range(lo, hi + 1):
                pg = int(self._page_tables[i, k])
                if pg != NULL_PAGE and self._pool.refcount(pg) > 1:
                    raise RuntimeError(
                        f"paged KV invariant violated: slot {i} write "
                        f"window [{int(starts[i])}, "
                        f"{int(starts[i]) + reserve}) maps shared page "
                        f"{pg} (refcount {self._pool.refcount(pg)})")

    def _stage(self):
        """Build (tokens, starts, nvalid, consumed, finishing) for this
        tick from current slot state. ``consumed[i]``: tokens written for
        slot i (its length advance); ``finishing[i]``: the tick's sample
        for slot i is a real next token.  The prefill source is
        ``slot.seq`` (the prompt, or ``prompt + tokens[:-1]`` on a
        preempt-resume); a resume slot's final replay chunk stages with
        ``finishing`` FALSE — its sample would be a re-prediction of the
        already-committed last token, so it is discarded and decode
        restarts from ``slot.last`` next tick (token-exact for greedy).

        ``prefill_budget`` bounds the PREFILL tokens staged per tick
        (decode feeds are never deferred): chunks are granted in
        priority order and may be narrowed (nvalid is runtime data —
        no retrace); a slot past the budget stages a scratch token at
        its current length with ``consumed`` 0 — the row is rewritten
        by the real chunk before any of that chunk's queries attend it,
        the same rollback argument spec-verify relies on.  This bounds
        how long a wall of batch prefill can displace an interactive
        slot's decode ticks — the chunked-prefill fairness knob
        (docs/SERVING.md)."""
        B, C = self.max_slots, self.chunk
        tokens = np.zeros((B, C), np.int32)
        starts = self._lengths.copy()
        nvalid = np.ones(B, np.int32)
        consumed = np.zeros(B, np.int32)
        finishing = [False] * B
        prefilling = [i for i, s in enumerate(self._slots)
                      if s.req is not None and s.off < len(s.seq)]
        rem = self._prefill_budget
        if rem is not None:
            prefilling.sort(key=lambda i: (self._slots[i].req._prank, i))
        for i in prefilling:
            slot = self._slots[i]
            w = min(C, len(slot.seq) - slot.off)
            if rem is not None:
                w = min(w, rem)
                rem -= w
            if w <= 0:
                continue   # budget spent: deferred (scratch, no advance)
            tokens[i, :w] = slot.seq[slot.off:slot.off + w]
            nvalid[i] = w
            consumed[i] = w
            finishing[i] = (not slot.resume
                            and slot.off + w >= len(slot.seq))
        for i, slot in enumerate(self._slots):
            if slot.req is None or slot.off < len(slot.seq):
                continue
            tokens[i, 0] = slot.last
            nvalid[i] = 1
            consumed[i] = 1
            finishing[i] = True
        return tokens, starts, nvalid, consumed, finishing

    def _finish(self, slot_idx, req):
        req.done = True
        if req.session is not None:
            # retain the finished turn's page chain as its session
            # BEFORE the release below drops the slot's refs — the next
            # turn resumes decoding from this tail
            self._session_install_locked(slot_idx, req)
        self._slots[slot_idx].req = None
        self._sampling_cache = None  # membership changed: restage
        self._lengths[slot_idx] = 0
        if self._paged:
            self._release_pages_locked(slot_idx)
        now = time.perf_counter()
        e2e = now - req._t_submit
        self._h_e2e.observe(e2e)
        self._slo["e2e"].observe(e2e)
        self._c["completed_tokens"].inc(len(req.tokens))
        req.lifecycle.update(t_finish=now, e2e_s=e2e,
                             tokens=len(req.tokens), aborted=False)
        if req._t_first is not None and len(req.tokens) > 1:
            tpot = (now - req._t_first) / (len(req.tokens) - 1)
            self._h_tpot.observe(tpot)
            req.lifecycle["tpot_s"] = tpot
        req._span_life.end(slot=slot_idx, tokens=len(req.tokens))
        self._flight.record(
            "req", phase="finish", rid=req.rid, engine=self._engine_id,
            slot=slot_idx, tokens=len(req.tokens),
            e2e_s=round(now - req._t_submit, 6))
        if req.on_token is not None:
            # end-of-stream terminal, AFTER this tick's token emits in
            # the same buffer — a streaming consumer sees every token,
            # then exactly one None
            self._stream_emit.append((req, None))
        req._event.set()

    def _tick_progress(self, req, t_ns):
        """Per-tick TPOT sample for one request: this tick committed
        ``len(req.tokens) - n_prev`` tokens since the mark at ``t_prev``,
        so the rolling window sees ``(t - t_prev) / committed`` — the
        per-token decode latency of THIS tick, not the request-lifetime
        mean (a mid-run slowdown shifts the /load p99 within one window,
        where the lifetime mean would launder it).  The tick that
        produced the FIRST token only plants the mark (that latency is
        TTFT's); called once per slot per tick, host floats only."""
        if req._t_first is None:
            return
        t = t_ns / 1e9   # perf_counter_ns and perf_counter share a clock
        n = len(req.tokens)
        mark = req._tick_mark
        if mark is not None:
            t_prev, n_prev = mark
            if n > n_prev and t > t_prev:
                self._slo["tpot"].observe((t - t_prev) / (n - n_prev))
        req._tick_mark = (t, n)

    def _commit_token(self, i, tok):
        """Record slot i's sampled token; returns True if the request
        completed."""
        slot = self._slots[i]
        req = slot.req
        if not req.tokens:
            req._t_first = time.perf_counter()
            ttft = req._t_first - req._t_submit
            req.lifecycle.update(t_first_token=req._t_first, ttft_s=ttft)
            self._h_ttft.observe(ttft)
            self._slo["ttft"].observe(ttft)
            self._slo_cls[req.priority]["ttft"].observe(ttft)
        req.tokens.append(tok)
        slot.last = tok
        self._c["tokens"].inc()
        if req.on_token is not None:
            # buffered under the lock, delivered by _flush_streams on
            # this driver thread after release (the hook may block —
            # that is the streaming backpressure)
            self._stream_emit.append((req, tok))
        if (len(req.tokens) >= req.max_new_tokens
                or (self.eos_token_id is not None
                    and tok == self.eos_token_id)):
            self._finish(i, req)
            return True
        return False

    def step(self) -> bool:
        """One engine tick: stage under the lock, run the device program
        unlocked (submit()/generate() stay responsive), commit under the
        lock. Returns False when there was nothing to do.

        Single-driver contract: while the auto_run loop is live, only the
        loop thread may tick — a second driver would re-enter the jitted
        tick with the DONATED cache buffers the in-flight call already
        invalidated (crash/corruption), so it raises instead.

        An escaping exception writes the flight-recorder ring to disk
        first (``observability/flight.py``): the dump carries the recent
        tick summaries and the failing requests' lifecycle events —
        the post-mortem an aggregate counter cannot give."""
        try:
            return self._step_impl()
        except BaseException as e:
            # the single-driver guard raise is a usage error, not an
            # engine crash: a caller retrying step() against a live
            # auto_run loop must not flood $PHT_FLIGHT_DIR with dumps
            # (or evict the ring's real history with 'crash' events)
            if not getattr(e, "_pht_usage_error", False):
                _flight.crash_dump(f"serving.step[{self._engine_id}]", e)
            raise

    def _after_tick(self, flavor, t0n, t1n, committed, **extra):
        """Per-tick event-level bookkeeping (all modes): the liveness
        beacon /healthz reads, the always-on flight tick summary, and —
        only while tracing is armed — the tick-level span."""
        _tr.heartbeat(f"serving.{self._engine_id}")
        self._flight.record(
            "tick", engine=self._engine_id, flavor=flavor,
            tickno=self._tickno, dur_us=(t1n - t0n) // 1000,
            committed=committed, **extra)
        if _tr.tracing_enabled():
            _tr.add_span(f"serving.tick.{flavor}", t0n, t1n,
                         engine=self._engine_id, tickno=self._tickno,
                         committed=committed, **extra)

    def _step_impl(self) -> bool:
        """Tick + streaming flush: committed tokens (and stream
        terminals) buffered under the lock during :meth:`_step_inner`
        are handed to their ``on_token`` hooks here, on the driver
        thread, lock-free.  A raising tick skips the flush — the
        auto_run loop's fail-all appends the terminal marks first and
        flushes everything, in order, itself."""
        busy = self._step_inner()
        self._flush_streams()
        if not busy and self._paged:
            # idle tick on the driver: cheap two-int fragmentation
            # check, compaction only when the pool is badly scattered
            self._maybe_defrag()
        return busy

    def _flush_streams(self):
        """Deliver buffered ``on_token`` emissions (driver thread only,
        no lock held — a blocking hook is the backpressure design and
        must never stall ``submit()``/introspection behind the engine
        lock).  A hook that RAISES is dropped with a flight mark
        instead of killing the tick loop: the stream consumer is the
        broken party, the other slots' requests are not."""
        with self._lock:
            if not self._stream_emit:
                return
            buf, self._stream_emit = self._stream_emit, []
        for req, tok in buf:
            try:
                req.on_token(tok)
            except Exception as e:  # noqa: BLE001 — consumer's bug
                self._flight.record(
                    "stream", phase="hook_error", rid=req.rid,
                    engine=self._engine_id, error=type(e).__name__)

    def _step_inner(self) -> bool:  # pht-lint: hot-root (tick body)
        # fault-injection drill points (observability/faults.py):
        # armed, they kill/fail/delay a tick deterministically — how
        # the fail-all path below and the crash-dump post-mortem are
        # drilled; disarmed each is one empty-dict probe per tick.
        # serving.step is the historical global point; the per-replica
        # serving.tick[<engine_id>] point is how a fleet drill kills
        # ONE replica of many in the same process.
        _faults.point("serving.step")
        _faults.point(self._tick_fault_point)
        with self._lock:
            if self._running and \
                    threading.current_thread() is not self._loop_thread:
                err = RuntimeError(
                    "engine is being driven by its auto_run loop; "
                    "step()/run_until_idle() from another thread would "
                    "re-enter the tick with donated caches — wait for the "
                    "loop to drain (shutdown()) instead")
                err._pht_usage_error = True   # step(): no crash dump
                raise err
            replays = self._admit()
            # decode half of the deadline budget (queue half runs in
            # _admit): a slot past its deadline frees before this tick
            # wastes another program dispatch on it
            self._expire_slots_locked()
            self._set_queue_gauges_locked()
            occ = sum(s.req is not None for s in self._slots)
            self._g_occupancy.set(occ)
            if occ > self._peak_occupancy:
                # paged-vs-dense admitted-concurrency evidence (bench)
                self._peak_occupancy = occ
            sampling = self._sampling_vectors()
            # live-slot mask, shared by every mode: the tick programs
            # run ALL slots (inactive rows carry scratch), and the MoE
            # stats observer must see only the real ones
            active = np.asarray([s.req is not None for s in self._slots])
            if self._pp > 1:
                if (not any(s.req is not None for s in self._slots)
                        and not self._inflight_live()):
                    return False
                mode = "pp"
                tokens, starts, nvalid, exit_wave = self._stage_pp_locked()
            elif not any(s.req is not None for s in self._slots):
                return False
            # after _admit, a pending request implies no free slot — so
            # "every active slot is decoding" is the spec/multi-window gate
            elif all(s.req is None or s.off >= len(s.seq)
                     for s in self._slots):
                last_toks = np.asarray([s.last for s in self._slots],
                                       np.int32)
                starts = self._lengths.copy()
                # speculate only when some active slot is greedy — an
                # all-sampling tick would pay the K+1-wide verify for 1
                # token/slot where the fused M-step window commits M
                mode = ("spec" if self._spec is not None
                        and bool((active & (sampling[1] == 0.0)).any())
                        else "multi")
            else:
                mode = "chunk"
                tokens, starts, nvalid, consumed, finishing = self._stage()
            if self._paged:
                self._check_write_windows_locked(starts)

        for i, req, skip, lengths, seq in replays:
            # deferred from _admit: the drafter's jitted ingest must not
            # dispatch under the engine lock (only this driver thread
            # mutates slot state, so running it here — before this
            # tick's device program and its post-verify ingest — is
            # order-equivalent to replaying inside _admit)
            self._replay_skipped_to_drafter(i, req, skip, lengths, seq)

        if mode == "pp":
            t0n = time.perf_counter_ns()
            nxt = self._run_pp_tick(tokens, starts, nvalid, sampling)
            t1n = time.perf_counter_ns()
            self._h_tick["pp"].observe((t1n - t0n) / 1e9)
            with self._lock:
                self._tickno += 1
                self._c["ticks"].inc()
                committed = self._commit_pp_exit_locked(exit_wave, nxt, t1n)
                self._after_tick("pp", t0n, t1n, committed,
                                 exit_wave=int(exit_wave))
            return True
        if mode == "spec":
            # draft-and-verify: slot state is stable outside the lock
            # (only this driver thread mutates it), so drafting and the
            # device tick run unlocked like the other modes
            drafts, ndraft = self._spec.propose(last_toks, starts)
            # only active greedy slots draft; sampled slots (per-request
            # temperature>0) advance 1 token/tick with exact sampling
            ndraft = np.where(active & (sampling[1] == 0.0), ndraft, 0)
            ndraft = ndraft.astype(np.int32)
            if not ndraft.any():
                # nothing proposed this tick (e.g. no n-gram repeats yet):
                # the K+1-wide verify would commit 1 token/slot — the
                # fused M-step window is strictly better, demote
                mode = "multi"
        if mode == "spec":
            toks = np.concatenate([last_toks[:, None], drafts], axis=1)
            t0n = time.perf_counter_ns()
            out = self._run_tick_spec(toks, starts, sampling,
                                      active=active, ndraft=ndraft)
            t1n = time.perf_counter_ns()
            self._h_tick["spec"].observe((t1n - t0n) / 1e9)
            from ..nn.decode import accept_lengths
            acc = accept_lengths(drafts, ndraft, out)
            with self._lock:
                self._tickno += 1
                self._c["ticks"].inc()
                self._c["spec_ticks"].inc()
                tron = _tr.tracing_enabled()
                tick_drafted = tick_accepted = tick_committed = 0
                nvalid = np.zeros(self.max_slots, np.int32)
                for i, slot in enumerate(self._slots):
                    if slot.req is None:
                        continue
                    req = slot.req   # _commit_token may free the slot
                    rid = req.rid
                    rem = req.max_new_tokens - len(req.tokens)
                    adv = int(acc[i]) + 1
                    nvalid[i] = adv
                    self._lengths[i] += adv
                    committed = 0
                    for t in range(adv):
                        committed += 1
                        if self._commit_token(i, int(out[i, t])):
                            break  # freed; later accepted tokens discarded
                    self._tick_progress(req, t1n)
                    # count only what the commit loop could use: the
                    # request budget (rem) bounds drafts, and the commit
                    # count additionally bounds accepted (EOS truncation)
                    # — otherwise the acceptance counters claim tokens
                    # the tokens counter never saw
                    d = min(int(ndraft[i]), rem)
                    a = min(int(acc[i]), committed)
                    self._c["spec_drafted"].inc(d)
                    self._c["spec_accepted"].inc(a)
                    tick_drafted += d
                    tick_accepted += a
                    tick_committed += committed
                    if tron:
                        # each slot's share of the fused verify tick on
                        # the REQUEST's lane (_tid=rid: one request, one
                        # swimlane): request id + acceptance outcome
                        _tr.add_span("serving.spec_verify", t0n, t1n,
                                     _tid=rid, rid=rid, slot=i, drafted=d,
                                     accepted=a, committed=committed)
                if tick_drafted:
                    self._h_accept.observe(tick_accepted / tick_drafted)
                self._after_tick("spec", t0n, t1n, tick_committed,
                                 drafted=tick_drafted,
                                 accepted=tick_accepted)
            if getattr(self._spec, "ingest_after_verify", True):
                # self-ingesting drafters (ModelDrafter) already wrote
                # these rows into their own cache during propose()
                self._spec.ingest(toks, starts, nvalid)
            return True
        if mode == "multi":
            t0n = time.perf_counter_ns()
            out = self._run_tick_multi(last_toks, starts, sampling,
                                       active=active)
            t1n = time.perf_counter_ns()
            self._h_tick["decode"].observe((t1n - t0n) / 1e9)
            with self._lock:
                self._tickno += 1
                self._c["ticks"].inc()
                tron = _tr.tracing_enabled()
                tick_committed = 0
                M = self._decode_window
                for i, slot in enumerate(self._slots):
                    if slot.req is None:
                        continue
                    req = slot.req   # _commit_token may free the slot
                    rid = req.rid
                    committed = 0
                    self._lengths[i] += M
                    for t in range(M):
                        committed += 1
                        if self._commit_token(i, int(out[i, t])):
                            break  # freed; later window tokens discarded
                    self._tick_progress(req, t1n)
                    tick_committed += committed
                    if tron:
                        _tr.add_span("serving.decode", t0n, t1n, _tid=rid,
                                     rid=rid, slot=i, window=M,
                                     committed=committed)
                self._after_tick("decode", t0n, t1n, tick_committed,
                                 window=M)
            if self._spec is not None:
                # an all-sampling window can still precede a greedy
                # request: mirror the M cache rows the window wrote so
                # the drafter stays in sync for later spec ticks
                M = self._decode_window
                chunk = np.concatenate([last_toks[:, None], out[:, :M - 1]],
                                       axis=1)
                self._spec.ingest(chunk, starts,
                                  np.where(active, M, 0).astype(np.int32))
            return True
        t0n = time.perf_counter_ns()
        nxt = self._run_tick(tokens, starts, nvalid, sampling, active)
        t1n = time.perf_counter_ns()
        self._h_tick["prefill"].observe((t1n - t0n) / 1e9)
        with self._lock:
            self._tickno += 1
            self._c["ticks"].inc()
            tron = _tr.tracing_enabled()
            tick_committed = 0
            for i, slot in enumerate(self._slots):
                if slot.req is None:
                    continue
                req = slot.req   # _commit_token may free the slot
                rid = req.rid
                was_prefill = slot.off < len(slot.seq)
                if was_prefill:
                    slot.off += int(consumed[i])
                    if (self._prefix is not None
                            and slot.off >= len(slot.seq)):
                        # prefill source fully consumed: register its
                        # FULL pages so later requests sharing the
                        # prefix (or this stream's own re-admission
                        # after another preemption) skip them.
                        # Before _commit_token — a request that finishes
                        # this very tick must donate its pages to the
                        # cache before _finish releases the slot's refs.
                        self._prefix.insert(
                            slot.seq, self._page_tables[i],
                            len(slot.seq) // self._page_size)
                self._lengths[i] += int(consumed[i])
                if finishing[i]:
                    self._commit_token(i, int(nxt[i]))
                    tick_committed += 1
                    self._tick_progress(req, t1n)
                if tron:
                    _tr.add_span(
                        "serving.prefill_chunk" if was_prefill
                        else "serving.decode",
                        t0n, t1n, _tid=rid, rid=rid, slot=i,
                        tokens=int(consumed[i]))
            self._after_tick("prefill", t0n, t1n, tick_committed)
        if self._spec is not None:
            # keep the drafter's mirror in sync with what the chunk tick
            # wrote (prefill chunks and the 1-wide decode feeds alike)
            self._spec.ingest(tokens, starts, consumed)
        return True

    def _run_tick_multi(self, last_toks, starts, sampling, active=None):
        import jax
        vec = sampling[0]
        temps_d, topks_d, topps_d = self._sampling_dev3(sampling)
        # the steady-state hot path: one jitted dispatch (sampling
        # vectors + page table already device-resident) + one fetch
        res = self._prog("_tick_multi", vec)(
            self._params, self._caches, last_toks,
            starts, temps_d, topks_d, topps_d, self._key,
            # single aligned int read by its only writer (driver thread)
            np.int32(self._tickno), **self._pt_kw())  # pht-lint: gil-atomic
        # designed once-per-tick fetch (see _run_tick); MoE stats are
        # the window's M-step means and ride the same fetch
        if self._moe:
            self._caches, out, st = res
            out, st = jax.device_get((out, st))
            self._observe_moe(st, np.ones(len(out), bool)
                              if active is None else active)
            return out
        self._caches, out = res
        return jax.device_get(out)

    def _inflight_live(self):
        return any(any(r is not None for r in rec[2])
                   for rec in self._inflight.values())

    def _stage_pp_locked(self):
        """Stage a pp tick (lock held by the caller). The ENTERING wave's
        snapshot (consumed, finishing, request identity) is recorded now;
        its slot state advances and its token commits when the wave
        EXITS, pp-1 ticks later — mid-flight, every stage must keep
        seeing the wave's entry-time cache positions."""
        pp = self._pp
        enter_wave = self._tickno % pp
        exit_wave = (self._tickno - (pp - 1)) % pp
        tokens, starts, nvalid, consumed, finishing = self._stage()
        self._inflight[enter_wave] = (
            consumed.copy(), list(finishing), [s.req for s in self._slots])
        return tokens, starts, nvalid, exit_wave

    def _commit_pp_exit_locked(self, exit_wave, nxt, t_ns):
        """Advance the exiting wave's slots; returns tokens committed."""
        rec = self._inflight.pop(exit_wave, None)
        if rec is None:
            return 0
        committed = 0
        consumed_e, finishing_e, reqs_e = rec
        lo, hi = exit_wave * self._wave, (exit_wave + 1) * self._wave
        for i in range(lo, hi):
            slot = self._slots[i]
            # commit only if the slot still holds the request the wave
            # carried (not freed/re-admitted mid-flight)
            if slot.req is None or slot.req is not reqs_e[i]:
                continue
            req = slot.req   # _commit_token may free the slot
            if slot.off < len(slot.seq):
                slot.off += int(consumed_e[i])
            self._lengths[i] += int(consumed_e[i])
            if finishing_e[i]:
                self._commit_token(i, int(nxt[i]))
                committed += 1
                self._tick_progress(req, t_ns)
        return committed

    def _loop(self):
        while True:
            try:
                # _step_impl, not step(): the loop writes its own crash
                # dump below AFTER the fail-all marks, so the on-disk
                # post-mortem carries the failing requests' terminal
                # events (step()'s dump would fire before them)
                busy = self._step_impl()
            except BaseException as e:  # noqa: BLE001 — a dead loop with
                # _running stuck True would hang every current AND future
                # request; fail them all with the cause instead (donated
                # caches may be gone, so the engine is not reusable)
                with self._lock:
                    def _fail(req, where):
                        req.error = e
                        # goodput accounting: every token the failed
                        # request generated is aborted work the caller
                        # never got — the /load report's goodput ratio
                        # reads completed/(completed+aborted)
                        self._c["aborted_tokens"].inc(len(req.tokens))
                        now = time.perf_counter()
                        req.lifecycle.update(
                            t_abort=now, aborted=True,
                            tokens=len(req.tokens), where=where,
                            error=type(e).__name__)
                        self._record_abort_locked(
                            req, where, type(e).__name__, now)
                        if req.on_token is not None:
                            # terminal AFTER any already-buffered tokens
                            self._stream_emit.append((req, None))
                        # close the lifecycle spans (no-ops when tracing
                        # is off) and leave a terminal flight mark — the
                        # failing requests are the ones a post-mortem
                        # most needs to see
                        req._span_queue.end(error=type(e).__name__)
                        req._span_life.end(error=type(e).__name__)
                        self._flight.record(
                            "req", phase="fail", rid=req.rid,
                            engine=self._engine_id, where=where,
                            error=type(e).__name__)
                        req._event.set()
                    for req in list(self._pending):
                        _fail(req, "pending")
                    self._pending.clear()
                    self._deadline_queued = 0
                    for i, slot in enumerate(self._slots):
                        if slot.req is not None:
                            _fail(slot.req, "slot")
                            slot.req = None
                            if self._paged:
                                self._release_pages_locked(i)
                    for rec in self._inflight.values():
                        for req in rec[2]:
                            if req is not None and not req._event.is_set():
                                _fail(req, "inflight")
                    self._inflight.clear()
                    # retained sessions die with the engine (their pages
                    # live in the donated caches that may be gone); busy
                    # sessions hold no refs — their pages were on slots
                    for sess in list(self._sessions.values()):
                        if self._paged and sess.pages:
                            self._pool.decref(sess.pages)
                    self._sessions.clear()
                    self._update_session_gauges_locked()
                    self._running = False
                    self._crashed = e
                # deliver the failed requests' stream terminals (and any
                # tokens the crashing tick had committed) — a streaming
                # consumer blocked on its queue must learn the replica
                # died, not hang until its own timeout
                self._flush_streams()
                # the loop thread dies on this raise: PIN the beacon so
                # it survives the thread's exit and goes stale — the
                # /healthz?max_age alert a crashed engine must leave
                # (beacon_ages GCs dead-thread beacons otherwise)
                _tr.pin_beacon(f"serving.{self._engine_id}")
                if not getattr(e, "_pht_usage_error", False):
                    _flight.crash_dump(
                        f"serving.step[{self._engine_id}]", e)
                raise
            if not busy:
                with self._lock:
                    if (not self._pending
                            and all(s.req is None for s in self._slots)):
                        self._running = False
                        # clean drain between bursts: drop the beacon so
                        # an IDLE engine doesn't 503 /healthz?max_age —
                        # the next burst's first tick re-adds it (the
                        # crash path above raises instead, keeping the
                        # beacon: going stale is the alert)
                        _tr.remove_beacon(f"serving.{self._engine_id}")
                        return

    def introspect_requests(self) -> dict:
        """In-flight slot table for ``/debug/requests`` (and debugging):
        one row per slot — request id, prompt progress, tokens generated,
        committed cache depth — plus the pending-queue depth.  Snapshot
        under the engine lock; called from the introspection server's
        thread, so it must stay cheap (it is: B small dicts)."""
        with self._lock:
            slots = []
            for i, slot in enumerate(self._slots):
                req = slot.req
                if req is None:
                    slots.append(None)
                    continue
                row = {
                    "rid": req.rid, "slot": i,
                    "prompt_len": int(len(req.prompt)),
                    "prompt_consumed": int(slot.off),
                    "generated": len(req.tokens),
                    "max_new_tokens": req.max_new_tokens,
                    "cache_len": int(self._lengths[i]),
                    "priority": req.priority,
                    "preempted": req._preempts,
                }
                if self._paged:
                    row["pages"] = len(self._slot_pages[i])
                slots.append(row)
            out = {"engine": self._engine_id, "tickno": self._tickno,
                   "running": self._running,
                   "draining": self._draining,
                   "pending": len(self._pending), "slots": slots,
                   # bounded terminal ring: where recently-aborted
                   # requests died (where="deadline" for budget aborts,
                   # pending/slot/inflight for a loop failure)
                   "recent_aborts": list(self._recent_aborts)}
            out["sessions"] = len(self._sessions)
            if self._paged:
                out["kv_pages_in_use"] = self._pool.allocated
                out["kv_pages_free"] = self._pool.free
                out["prefix_cached_pages"] = (
                    len(self._prefix) if self._prefix is not None else 0)
            return out

    def slo_windows(self) -> dict:
        """The live rolling SLO windows (``{"ttft", "tpot", "e2e",
        "queue_wait"} -> SlidingWindowHistogram``) — the percentile
        source behind :meth:`load_report`'s ``slo`` block, exposed for
        in-process fleet aggregation (``metrics.merged_percentiles``
        pools several replicas' windows without losing the
        never-exceeds-observed-max clamp).  In-process only: HTTP
        replicas federate through ``/load``'s serialized percentiles
        instead."""
        return dict(self._slo)

    def load_report(self) -> dict:
        """The machine-readable load/capacity report — the versioned
        JSON document the ``/load`` endpoint serves and a least-loaded
        router polls (ROADMAP item 2; schema contract:
        docs/OBSERVABILITY.md, "SLO telemetry and the /load report").

        One snapshot under the engine lock (host dicts and counters
        only — no device touch), so polling never stalls a tick:

        - ``slots``/``queue``: free capacity and how long the
          longest-waiting queued request has been waiting since its
          last enqueue (submit or preemption re-queue), plus the
          per-priority-class breakdown (``queue.classes``) — a
          least-loaded router scoring total depth alone would let an
          interactive queue starve unseen behind a deep batch queue.
        - ``admission``: the headroom a router sizes a request against —
          largest admissible ``prompt + max_new`` right now (page-exact
          in paged mode via ``paged.tokens_admittable``, ``max_len``
          minus the write-window reserve in dense), plus the paged
          pool's free/used pages.
        - ``modes``: what this replica is (spec/quant/MoE/paged/pp) —
          a router must not mix replicas with different latency shapes
          in one SLO pool blindly.
        - ``slo``: rolling TTFT/TPOT/e2e/queue-wait percentiles over the
          last ``slo_window_s`` seconds (None when no traffic — never
          NaN, which is not JSON).
        - ``goodput``: completed vs aborted generated tokens and their
          ratio (None before any token).
        """
        reserve = max(self.chunk, self.spec_k + 1)
        with self._lock:
            now = time.perf_counter()
            active = sum(s.req is not None for s in self._slots)
            free_slots = self.max_slots - active
            oldest = max((now - r._t_queued for r in self._pending),
                         default=0.0)
            cls_q = {c: {"depth": 0, "oldest_wait_s": 0.0}
                     for c in PRIORITY_RANK}
            for r in self._pending:
                row = cls_q[r.priority]
                row["depth"] += 1
                w = round(now - r._t_queued, 6)
                if w > row["oldest_wait_s"]:
                    row["oldest_wait_s"] = w
            completed = int(self._c["completed_tokens"].value)
            aborted = int(self._c["aborted_tokens"].value)
            report = {
                "version": 1,
                "engine": self._engine_id,
                "ts": time.time(),
                "running": self._running,
                # a draining replica still finishes queued + inflight
                # work but refuses submits — a router must not dispatch
                # to it (field added within version 1: consumers that
                # don't know it keep working, routers that do stop
                # placing here the poll after drain() is called)
                "draining": self._draining,
                "tickno": self._tickno,
                "slots": {"max": self.max_slots, "active": active,
                          "free": free_slots},
                "queue": {"depth": len(self._pending),
                          "oldest_wait_s": round(oldest, 6),
                          # per-class block (added within version 1):
                          # all classes always present, zeroed when
                          # idle, so router code never key-checks
                          "classes": cls_q},
                "modes": {"cache": self.cache_mode,
                          "spec_k": self.spec_k,
                          "quant": self._quantized,
                          "moe": self._moe,
                          "pp": self._pp},
                "slo": {"window_s": self._slo_window_s,
                        **{k: h.percentiles()
                           for k, h in self._slo.items()},
                        # per-class TTFT/queue-wait percentiles (added
                        # within version 1): the control signal the
                        # scheduler exists to move — aggregate p99
                        # launders an interactive tail under batch bulk
                        "classes": {c: {k: h.percentiles()
                                        for k, h in hs.items()}
                                    for c, hs in self._slo_cls.items()}},
                # scheduler block (added within version 1): the knobs a
                # fleet operator tunes + the preemption count goodput
                # regressions get correlated against
                "scheduler": {
                    "preemptions": int(self._c["preemptions"].value),
                    "preempt_replay_tokens": int(
                        self._c["preempt_replay_tokens"].value),
                    "preempt": self._preempt,
                    "preempt_limit": self._preempt_limit,
                    "prefill_budget": self._prefill_budget,
                    "priority_aging_s": self._aging_s},
                "goodput": {
                    "completed_tokens": completed,
                    "aborted_tokens": aborted,
                    "ratio": (completed / (completed + aborted)
                              if completed + aborted else None)},
            }
            admission = {"reserve_tokens": reserve}
            # the per-slot caps every request faces regardless of pool
            # state: max_len minus the write-window reserve, and the
            # model's position table (submit() refuses past either)
            slot_cap = self.max_len - reserve
            max_pos = getattr(self.model.config,
                              "max_position_embeddings", None)
            if max_pos is not None:
                slot_cap = min(slot_cap, int(max_pos))
            sess_pages = set()
            for s in self._sessions.values():
                sess_pages.update(s.pages)
            sess_evictable = self._session_evictable_pages_locked()
            # sessions block (added within version 1): how much of the
            # pool conversation retention is pinning, and how much of
            # that admission pressure could take back RIGHT NOW
            report["sessions"] = {
                "count": len(self._sessions),
                "retained_pages": len(sess_pages),
                "evictable_pages": sess_evictable}
            if self._paged:
                from .paged import tokens_admittable
                # admission evicts cache-only prefix pages AND LRU
                # sessions' exclusively-held pages to cover a shortfall
                # (_paged_admit_locked), so the free list alone
                # UNDERSTATES what would actually admit — the router
                # contract is "would this request fit RIGHT NOW",
                # eviction included (sessions never starve admission)
                evictable = (self._prefix.cached_only()
                             if self._prefix is not None else 0)
                headroom = min(
                    tokens_admittable(
                        self._pool.free + evictable + sess_evictable,
                        reserve, self._page_size),
                    slot_cap)
                admission.update(
                    kv_pages_free=self._pool.free,
                    kv_pages_evictable=evictable,
                    kv_pages_in_use=self._pool.allocated,
                    page_size=self._page_size,
                    # a free slot is still required: pages alone don't
                    # admit when every slot is occupied
                    headroom_tokens=headroom if free_slots else 0)
            else:
                admission["headroom_tokens"] = (slot_cap if free_slots
                                                else 0)
            report["admission"] = admission
            if self._prefix is not None:
                # cache-affinity signal (added within version 1): chain
                # digests of resident radix-cache nodes.  A router
                # hashing a prompt's page-aligned prefixes the same way
                # (paged.page_digests) matches the deepest digest here
                # to find the replica already holding those KV pages.
                # Bounded (most-recent first) so a warm cache never
                # bloats the poll document.
                # retained sessions' chain digests lead: a returning
                # turn's page_digests match them deepest here, which is
                # exactly the fleet-tier session stickiness signal —
                # then the cache's recency-ordered digests fill the cap
                digs = []
                seen = set()
                for s in self._sessions.values():
                    for d in s.digests:
                        if d not in seen:
                            seen.add(d)
                            digs.append(d)
                for d in self._prefix.digests(self.PREFIX_DIGEST_LIMIT):
                    if d not in seen:
                        seen.add(d)
                        digs.append(d)
                report["prefix_digest"] = {
                    "algo": "crc32-pages",
                    "page_size": self._page_size,
                    "digests": digs[:self.PREFIX_DIGEST_LIMIT]}
            return report

    @property
    def kv_pages_in_use(self) -> int:
        """Allocated pool pages (0 in dense mode) — includes pages held
        only by the prefix cache or by retained sessions;
        :meth:`drop_prefix_cache` + :meth:`drop_sessions` reclaim those,
        after which a drained engine must read 0 (the pool-leak assert
        tools/perf_gate.py gates via the bench row)."""
        return self._pool.allocated if self._paged else 0

    @property
    def kv_pages_free(self) -> int:
        return self._pool.free if self._paged else 0

    def drop_prefix_cache(self) -> int:
        """Release every cached prefix page (HBM reclaim / leak checks);
        returns how many the cache held.  Pages a live slot still maps
        stay allocated until that slot frees."""
        with self._lock:
            if self._prefix is None:
                return 0
            n = self._prefix.drop()
            self._g_pages_used.set(self._pool.allocated)
            self._g_pages_free.set(self._pool.free)
            return n

    def run_until_idle(self, max_ticks=100000):
        """Drive the engine synchronously (single-threaded use/tests).
        Raises if the auto_run loop is concurrently driving (see
        :meth:`step`'s single-driver contract)."""
        for _ in range(max_ticks):
            if not self.step():
                with self._lock:
                    if (not self._pending
                            and all(s.req is None for s in self._slots)):
                        # mirror the auto_run loop's idle-drain: a
                        # synchronously driven engine must not leave a
                        # forever-stale beacon 503ing /healthz?max_age
                        _tr.remove_beacon(f"serving.{self._engine_id}")
                return
        raise RuntimeError("engine did not drain in max_ticks")

    def drain(self, timeout=60.0):
        """Graceful removal, the half hard ``shutdown(timeout=)`` does
        not give: stop ADMITTING (``submit`` raises
        :class:`EngineDraining`), let queued + inflight requests run to
        completion, then drop the liveness beacon — the engine object
        stays constructed (introspection/metrics keep answering) until
        :meth:`shutdown` completes the teardown.  This is what a fleet
        router calls to remove a replica without failing a single
        request (``FleetRouter.drain``).

        A sync-driven engine (``auto_run=False``, or an auto_run engine
        whose loop has idled out) is driven to completion HERE — drain
        becomes the driver, honoring the single-driver contract (it
        only steps while the loop is not running).  Idempotent; raises
        ``TimeoutError`` if the backlog outlives ``timeout``, and
        ``RuntimeError`` (crash as ``__cause__``) if the engine's loop
        CRASHED instead of draining — the emptied slots/queue then
        mean the backlog was failed, not completed, and the pinned
        crash beacon is left alone (going stale IS the alert)."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                running = self._running
                crashed = self._crashed
                idle = (not self._pending
                        and all(s.req is None for s in self._slots)
                        and not self._inflight_live())
            if crashed is not None and not running:
                raise RuntimeError(
                    f"engine {self._engine_id} crashed while draining "
                    f"({type(crashed).__name__}): its queued + inflight "
                    f"requests were FAILED, not completed — this is not "
                    f"a clean removal") from crashed
            if idle and not running:
                with self._lock:
                    # graceful session eviction: a draining replica
                    # DONATES every retained chain to the prefix cache,
                    # so a conversation re-admitted elsewhere-then-back
                    # (or replayed by the router on a survivor) replays
                    # from cached pages instead of dying mid-dialogue
                    for sid in list(self._sessions):
                        if not self._sessions[sid].busy:
                            self._evict_session_locked(sid, donate=True)
                # same clean-drain contract as the loop's idle exit: a
                # DRAINED engine must not 503 /healthz?max_age forever
                _tr.remove_beacon(f"serving.{self._engine_id}")
                return
            if running:
                time.sleep(0.005)   # the auto_run loop is finishing it
            else:
                self.step()         # sync-driven: drain is the driver
        raise TimeoutError(
            f"engine {self._engine_id} did not drain in {timeout}s")

    def shutdown(self, timeout=60.0):
        """Wait for the background loop to drain and stop — call before
        interpreter exit so a daemon thread isn't killed mid-device-call
        (which aborts the process from PJRT's C++).  Also drops this
        engine's labelled series from the process-wide registry (engine
        churn must not grow it forever); ``self.stats`` holds its own
        counter handles, so it stays readable after shutdown."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._running:
                    self._registry.drop_labels(engine=self._engine_id)
                    _tr.unregister_introspection_source(self._engine_id)
                    # a shut-down engine must vanish from the router's
                    # /load poll (and the /debug mirror) immediately,
                    # not only when the weak refs die
                    _tr.unregister_load_source(self._engine_id)
                    _tr.unregister_introspection_source(
                        f"{self._engine_id}.load")
                    # clean shutdown: a gone engine must not leave a
                    # forever-stale beacon 503ing /healthz?max_age (a
                    # CRASHED loop keeps its beacon — stale IS the alert)
                    _tr.remove_beacon(f"serving.{self._engine_id}")
                    return
            time.sleep(0.005)
        raise TimeoutError("engine loop did not drain before timeout")
