"""AnalysisPredictor equivalent.

Ref ``AnalysisPredictor`` (``paddle/fluid/inference/api/analysis_predictor.h:95``):
``ZeroCopyRun`` (``:182``), input/output handles (``GetInputTensor``), the
``PredictorPool`` (``api/paddle_inference_api.h``) and ``Clone``.

TPU-native execution: the loaded artifact is a StableHLO program
(``jax.export``); a ``jax.jit`` wrapper is the NaiveExecutor+engine — first
``run()`` compiles (and caches, incl. persistently via
``Config.set_optim_cache_dir``), later runs replay the executable.
Weights stay resident on device; feeds move H2D on ``copy_from_cpu``;
outputs stay on device until ``copy_to_cpu`` — the ZeroCopy contract.
"""

from __future__ import annotations

import io as _io
import json
import pickle
import zipfile
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config

_JIT_MAGIC = "paddle_hackathon_tpu.jit.v1"


def get_version() -> str:
    from .. import __version__
    return __version__


class Tensor:
    """Zero-copy input/output handle (ref ``ZeroCopyTensor``
    ``paddle/fluid/inference/api/details/zero_copy_tensor.cc``)."""

    def __init__(self, name: str, device):
        self.name = name
        self._device = device
        self._value = None  # jax.Array on the target device
        self._shape_hint = None

    # -- input side --------------------------------------------------------
    def reshape(self, shape):
        """Declare the expected shape; validated on the next bind (shapes
        are otherwise taken from the bound array at run time)."""
        self._shape_hint = tuple(int(d) for d in shape)

    def copy_from_cpu(self, arr):
        arr = np.asarray(arr)
        hint = self._shape_hint
        if hint is not None and tuple(arr.shape) != hint:
            raise ValueError(
                f"tensor '{self.name}': bound array shape {arr.shape} does "
                f"not match reshape({list(hint)})")
        self._value = jax.device_put(arr, self._device)

    def share_external_data(self, tensor):
        """Bind an already-on-device array without a copy."""
        val = getattr(tensor, "_value", tensor)
        self._value = val

    # -- output side -------------------------------------------------------
    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(f"tensor '{self.name}' has no data; run() first")
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def type(self):
        return self._value.dtype if self._value is not None else None


class _BuildCtx:
    """Mutable context the pass pipeline operates on."""

    def __init__(self, config: Config):
        self.config = config
        self.donate_feeds = False
        self.resident_params = False


def _load_artifact(config: Config):
    """Load a static artifact (prefix.pdmodel raw StableHLO +
    prefix.pdiparams pickle), a jit zip artifact (MAGIC member), or a
    ``save_for_serving`` directory ({config.json, params.npz} — bf16 or
    weight-only-quantized; the quantized artifact rebuilds with fused
    dequant-GEMM Linears, so Predictor serves int8/fp8 weights through
    the same ZeroCopy interface)."""
    import os
    prog = config.prog_file()
    if prog is None:
        raise ValueError("Config has no model file; call set_model()")
    if os.path.isdir(prog) and os.path.exists(
            os.path.join(prog, "config.json")):
        from .serving import load_for_serving
        model = load_for_serving(prog)
        params, bufs = model.functional_state()
        return ("serving", model, params, bufs, ["input_ids"], 1)
    path = prog if prog.endswith(".pdmodel") else prog + ".pdmodel"
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path, "r") as zf:
            names = zf.namelist()
            if "MAGIC" not in names or zf.read("MAGIC").decode() != _JIT_MAGIC:
                raise ValueError(
                    f"not a jit inference artifact: {path} (missing or "
                    f"unsupported MAGIC; expected {_JIT_MAGIC!r})")
            exported = jax.export.deserialize(zf.read("program.stablehlo"))
            meta = json.loads(zf.read("meta.json"))
            npz = np.load(_io.BytesIO(zf.read("params.npz")))
            params = [npz[f"p{i}"] for i in range(meta["n_params"])]
            buffers = [npz[f"b{i}"] for i in range(meta["n_buffers"])]
            feed_names = [f"x{i}" for i in range(len(meta["input_specs"]))]
            # out tree is (outputs..., new_buffers...): recover the
            # user-visible output count from the exported signature so
            # get_output_names() is correct before the first run()
            n_out = len(exported.out_avals) - meta["n_buffers"]
            return ("jit", exported, params, buffers, feed_names, n_out)
    with open(path, "rb") as f:
        exported = jax.export.deserialize(f.read())
    params_path = config.params_file()
    if params_path is None:
        prefix = path[:-len(".pdmodel")]
        params_path = prefix + ".pdiparams"
    with open(params_path, "rb") as f:
        meta = pickle.load(f)
    return ("static", exported, meta["params"], None, meta["feed_names"],
            meta["fetch_count"])


class Predictor:
    """Ref ``AnalysisPredictor`` (``analysis_predictor.h:95``)."""

    def __init__(self, config: Config, _shared=None):
        self._config = config
        ctx = _BuildCtx(config)
        if config.ir_optim():
            config.pass_builder().apply(ctx)
        self._ctx = ctx

        backend = "tpu" if config.use_gpu() else "cpu"
        try:
            devs = jax.devices(backend)
        except RuntimeError:
            devs = jax.devices()
        self._device = devs[min(config.gpu_device_id(), len(devs) - 1)]

        if _shared is not None:  # Clone(): share weights + executable
            (self._kind, self._exported, self._params, self._bufs,
             feed_names, self._fetch_count, self._compiled) = _shared
        else:
            (self._kind, self._exported, params, bufs, feed_names,
             self._fetch_count) = _load_artifact(config)
            if self._ctx.resident_params:
                # ZeroCopy weights: pinned on the target device once
                put = (lambda a: jax.device_put(jnp.asarray(a), self._device))
            else:
                # pass is disabled (or ir_optim off): weights stay on host
                # and transfer on each run
                put = np.asarray
            if self._kind == "serving":
                # the live model already holds these arrays (run_fn
                # closes over it) — tree-mapping a put here would keep a
                # SECOND full weight copy alive for the Predictor's
                # lifetime, doubling the footprint the quantized
                # artifact exists to halve
                self._params, self._bufs = params, bufs
            else:
                # list-shaped pdmodel/jit artifacts: resident-params
                # pins to the target device, else host copies per run
                self._params = jax.tree.map(put, params)
                self._bufs = (jax.tree.map(put, bufs)
                              if bufs is not None else None)
            self._compiled = self._build_runner()

        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n, self._device) for n in feed_names}
        self._feed_names = feed_names
        self._outputs: Dict[str, Tensor] = {}
        self._output_names: List[str] = []

    def _build_runner(self):
        exported = self._exported
        if self._kind == "serving":
            # the artifact is a live model (save_for_serving dir): the
            # runner is one jitted functional forward — quantized
            # Linears route to the fused dequant GEMM inside this
            # program exactly as they do in ServingEngine's tick
            from ..core.tensor import Tensor
            from ..nn.layer import functional_call
            model = exported

            def run_fn(args, params, bufs):
                logits = functional_call(model, params, (Tensor(args[0]),),
                                         buffers=bufs, training=False)
                return [logits]
        elif self._kind == "static":
            def run_fn(feeds, params):
                return exported.call(feeds, params)
        else:
            def run_fn(args, params, bufs):
                # raw key form — must match the aval jit.save exported
                # (typed keys don't serialize on jax<0.6)
                key = jax.random.PRNGKey(0)
                outs, _ = exported.call(params, bufs, key, *args)
                return outs
        # Two executables: the zero-copy path must NOT donate feeds (handles
        # keep referencing them across run() calls — the reference's
        # ZeroCopyRun contract allows re-running with the same bound inputs);
        # the convenience run(inputs) path re-binds feeds every call, so
        # donating them there is safe and is what enable_memory_optim buys.
        keep = jax.jit(run_fn)
        if self._ctx.donate_feeds:
            from ..observability.sanitizers import sanitize_donation
            donating = sanitize_donation(
                jax.jit(run_fn, donate_argnums=(0,)),
                donate_argnums=(0,), site="predictor.run")
        else:
            donating = keep
        return (keep, donating)

    # -- handles -----------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    get_input_tensor = get_input_handle

    def get_output_names(self) -> List[str]:
        if not self._output_names:
            n = self._fetch_count if self._fetch_count is not None else 1
            self._output_names = [f"fetch_{i}" for i in range(n)]
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        if name not in self._outputs:
            self._outputs[name] = Tensor(name, self._device)
        return self._outputs[name]

    get_output_tensor = get_output_handle

    # -- execution ---------------------------------------------------------
    def run(self, inputs: Optional[List] = None):
        """ZeroCopyRun (ref ``analysis_predictor.h:182``). With ``inputs``
        given, behaves like the new paddle_infer convenience API: binds them
        positionally and returns numpy outputs."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._inputs[name].copy_from_cpu(np.asarray(arr))
        feeds = []
        for n in self._feed_names:
            h = self._inputs[n]
            if h._value is None:
                raise RuntimeError(f"input '{n}' not set; copy_from_cpu first")
            feeds.append(h._value)
        runner = self._compiled[1 if inputs is not None else 0]
        donated = inputs is not None and self._ctx.donate_feeds
        if self._kind == "static":
            outs = runner(feeds, self._params)
        else:
            outs = runner(feeds, self._params, self._bufs)
        if donated:
            # feed buffers are gone; force a clear error (not a deleted-buffer
            # crash) if a later zero-copy run() reuses the stale handles
            for n in self._feed_names:
                self._inputs[n]._value = None
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        outs = jax.tree.leaves(outs)
        self._output_names = [f"fetch_{i}" for i in range(len(outs))]
        for i, v in enumerate(outs):
            self.get_output_handle(self._output_names[i])._value = v
        if inputs is not None:
            return [np.asarray(v) for v in outs]
        return True

    def clone(self) -> "Predictor":
        shared = (self._kind, self._exported, self._params, self._bufs,
                  list(self._feed_names), self._fetch_count, self._compiled)
        return Predictor(self._config, _shared=shared)

    def clear_intermediate_tensor(self):
        for h in self._outputs.values():
            h._value = None


def create_predictor(config: Config) -> Predictor:
    """Ref ``CreatePaddlePredictor`` (``api/analysis_predictor.cc``)."""
    return Predictor(config)


class PredictorPool:
    """Ref ``PredictorPool`` (``api/paddle_inference_api.h``): one main
    predictor + size-1 clones sharing weights/executable."""

    def __init__(self, config: Config, size: int = 1):
        main = create_predictor(config)
        self._preds = [main] + [main.clone() for _ in range(max(0, size - 1))]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]
