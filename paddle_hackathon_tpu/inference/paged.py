"""Host-side paged-KV bookkeeping: page pool allocator + radix prefix cache.

The serving engine's dense layout reserves ``max_len`` cache rows per slot
the moment a request is admitted, so short requests strand HBM and the
slot count — not FLOPs — caps concurrency.  PagedAttention (vLLM) and
RadixAttention (SGLang) showed that block-granular KV lifts batch size
2-4x at equal HBM.  This module owns the HOST side of that design:

- :class:`PagePool` — a free-list allocator with per-page refcounts over
  the device page pool (``[num_pages, page_size, heads, head_dim]`` per
  layer).  Page 0 is reserved as the NULL page: page-table rows of
  inactive slots point at it, so the tick program's unconditional writes
  for empty batch rows land in scratch instead of another request's KV.
- :class:`PrefixCache` — a radix tree over page-granular token blocks.
  A finished (or still-prefilling) request registers its FULL prompt
  pages keyed by their token content; a later request whose prompt
  shares that prefix maps the same physical pages (refcount++) and skips
  re-prefilling them.  Shared pages are never written again: sharing is
  restricted to full pages strictly before a request's first write
  position, and the hit is capped at ``len(prompt) - 1`` tokens (the
  engine must re-prefill at least the last prompt token to produce
  logits), rounded DOWN to a page boundary — the dropped tail page is
  re-computed into a private page, which is the copy-on-write fork:
  "copy" by recompute, no device memcpy machinery.

Everything here is plain numpy/python under the engine lock; the device
side (pools, page tables, the gather/scatter attention) lives in
``models/gpt.py`` + ``incubate/nn/kernels/paged_attention.py``.
"""

from __future__ import annotations

import heapq
import zlib
from typing import List, Optional

import numpy as np

NULL_PAGE = 0


def pages_for(need: int, reserve: int, page_size: int) -> int:
    """Worst-case page footprint of a request needing ``need`` committed
    cache rows with a ``reserve``-token write window.

    The widest in-flight write starts at the last committed length
    (``need - 1``) and spans ``reserve`` tokens, so rows up to
    ``need + reserve - 2`` can be touched — and a window narrower than a
    page can still STRADDLE a page boundary, so the reservation must be
    computed on the final row index, not by summing token counts
    (reserving ``max(chunk, spec_k+1)`` tokens undercounts by one page
    exactly when the window straddles)."""
    last_row = need + reserve - 2
    return last_row // page_size + 1


def page_digests(prompt, page_size: int) -> List[int]:
    """Running crc32 digest per page-aligned prefix of ``prompt``: entry
    ``k-1`` covers tokens ``[0, k*page_size)``, capped at
    ``(len(prompt) - 1) // page_size`` full pages (the same cap
    :meth:`PrefixCache.match` applies — the engine must re-prefill at
    least the last prompt token).

    Bytes-identical to the chain digests :meth:`PrefixCache.digests`
    publishes through the ``/load`` report's ``prefix_digest`` block
    (each radix node's digest is the crc32 of the concatenated int32
    page-key bytes from the root), so set membership answers "does this
    replica already hold my prompt's first k pages" without shipping
    token content — the fleet router's cache-affinity signal
    (``inference/fleet.py``)."""
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    P = int(page_size)
    limit = max(0, (len(prompt) - 1) // P)
    out, crc = [], 0
    for k in range(limit):
        crc = zlib.crc32(prompt[k * P:(k + 1) * P].tobytes(), crc)
        out.append(crc)
    return out


def tokens_admittable(free_pages: int, reserve: int, page_size: int) -> int:
    """Largest committed-row need (``prompt + max_new``) a SINGLE fresh
    request could reserve from ``free_pages`` — the exact inverse of
    :func:`pages_for`, published as the ``/load`` report's paged
    admission headroom so a router can answer "would THIS request fit
    here right now" without replaying the allocator.  0 when even a
    1-token request would not fit (the write window alone exceeds the
    free pool)."""
    return max(0, int(free_pages) * int(page_size) - int(reserve) + 1)


class PagePool:
    """Free-list page allocator with refcounts.

    ``num_pages`` counts the DEVICE pool's leading dim; page 0 is the
    reserved null/scratch page, so ``usable = num_pages - 1``."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._ref = np.zeros(self.num_pages, np.int32)
        # LIFO free list: recently-freed pages are re-used first (their
        # rows are hottest in cache-of-caches senses and it keeps the
        # pool's touched footprint small under light load)
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))

    @property
    def usable(self) -> int:
        return self.num_pages - 1

    @property
    def free(self) -> int:
        return len(self._free)

    @property
    def allocated(self) -> int:
        return self.usable - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None (caller may evict+retry)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def incref(self, pages) -> None:
        for p in np.atleast_1d(pages):
            if self._ref[p] <= 0:
                raise ValueError(f"incref of unallocated page {int(p)}")
            self._ref[p] += 1

    def decref(self, pages) -> None:
        for p in np.atleast_1d(pages):
            p = int(p)
            if p == NULL_PAGE or self._ref[p] <= 0:
                raise ValueError(f"decref of unallocated page {p}")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def allocated_ids(self) -> List[int]:
        """Ascending ids of every allocated page (refcount > 0, null page
        excluded) — the compaction planner's input."""
        return [int(p) for p in np.nonzero(self._ref[1:])[0] + 1]

    def highest_allocated(self) -> int:
        """Highest allocated page id, or 0 when the pool is empty — the
        fragmentation signal: ``highest_allocated() + 1`` much larger
        than ``allocated`` means live pages are scattered across a
        mostly-free pool and a compaction would shrink the touched
        footprint."""
        ids = np.nonzero(self._ref[1:])[0]
        return int(ids[-1] + 1) if len(ids) else 0

    def compaction_plan(self) -> List[tuple]:
        """``[(src, dst), ...]`` moves that pack every allocated page
        into the lowest ids ``1..allocated`` (null page stays put).
        Sources and destinations are provably disjoint: dsts are the
        FREE ids among ``1..allocated`` and srcs are the allocated ids
        above ``allocated``, so applying the moves in any order is safe
        and the device copy can be one batched gather/scatter.  Empty
        when the pool is already packed."""
        ids = self.allocated_ids()
        n = len(ids)
        dsts = [p for p in range(1, n + 1) if self._ref[p] == 0]
        srcs = [p for p in ids if p > n]
        assert len(srcs) == len(dsts)
        return list(zip(srcs, dsts))

    def apply_moves(self, moves) -> List[tuple]:
        """Commit a :meth:`compaction_plan` to the host bookkeeping:
        refcounts move ``src -> dst`` and the free list is rebuilt.
        Each pair is re-validated (``src`` still allocated, ``dst``
        still free) so a page freed between planning and commit — e.g. a
        concurrent :meth:`PrefixCache.drop` from another thread — is
        skipped rather than corrupting the pool; the device copy wrote
        garbage into a free page, which is harmless.  Returns the pairs
        actually applied (the caller remaps its page tables from
        these)."""
        applied = []
        for src, dst in moves:
            src, dst = int(src), int(dst)
            if self._ref[src] <= 0 or self._ref[dst] != 0:
                continue
            self._ref[dst] = self._ref[src]
            self._ref[src] = 0
            applied.append((src, dst))
        # LIFO order with the lowest ids last keeps the packed tail of
        # the pool as the first pages handed out next
        self._free = [p for p in range(self.num_pages - 1, 0, -1)
                      if self._ref[p] == 0]
        return applied

    def cow(self, page: int):
        """Copy-on-write fork of ``page``: exclusively-owned pages are
        returned as-is; shared pages trade this caller's reference for a
        fresh private page.  Returns ``(page_id, forked)`` — ``forked``
        means the caller must (re)produce the page's contents — or
        ``None`` when the pool is exhausted (the original reference is
        kept).

        The serving engine's prefix path does NOT call this today: its
        fork is the match round-down + recompute (module docstring), so
        a slot's write window only ever maps exclusive pages (the tick
        tripwire asserts it).  ``cow`` is the allocator-level primitive
        for forking an in-place tail — what multi-turn suffix caching
        (ROADMAP item 1 follow-up) needs when a finished request's LAST
        page is shared and the next turn must extend it."""
        if self._ref[page] <= 0:
            raise ValueError(f"cow of unallocated page {int(page)}")
        if self._ref[page] == 1:
            return int(page), False
        fresh = self.alloc(1)
        if fresh is None:
            return None
        self.decref(page)
        return fresh[0], True


class _Node:
    __slots__ = ("key", "page", "parent", "children", "stamp", "digest")

    def __init__(self, key, page, parent):
        self.key = key
        self.page = int(page)
        self.parent = parent
        self.children = {}
        self.stamp = 0
        # chain digest root->node: crc32 over the concatenated page-key
        # bytes, computed incrementally (crc32's running-start form) —
        # equals page_digests(prompt, P)[depth-1] for the prompt whose
        # pages this chain holds
        self.digest = zlib.crc32(key, parent.digest if parent else 0)


class PrefixCache:
    """Radix tree over page-granular prompt blocks -> physical page ids.

    The cache holds its OWN reference on every registered page, so a
    cached page outlives the request that wrote it; :meth:`evict` drops
    least-recently-matched leaves whose page nobody else references (so
    eviction can never free a page an active slot still maps)."""

    def __init__(self, pool: PagePool):
        self._pool = pool
        self._root: dict = {}          # key -> _Node (top level)
        self._nodes: List[_Node] = []  # all nodes, for LRU scans
        self._clock = 0
        self.hits = 0                  # pages matched (for tests)

    def __len__(self):
        return len(self._nodes)

    @property
    def pages(self):
        return [n.page for n in self._nodes]

    def cached_only(self) -> int:
        """Pages :meth:`evict` could free RIGHT NOW: nodes whose entire
        subtree nobody else references (eviction frees leaf-up, so a
        refcount-1 node pinned under a live descendant does not count —
        that shape arises when two slots prefill overlapping prompts
        concurrently and the longer one's insert hangs its novel tail
        page under the other's already-registered prefix nodes)."""
        def walk(children):
            total, clean = 0, True
            for nd in children.values():
                sub_total, sub_clean = walk(nd.children)
                nd_clean = (sub_clean
                            and self._pool.refcount(nd.page) == 1)
                total += sub_total + (1 if nd_clean else 0)
                clean = clean and nd_clean
            return total, clean
        return walk(self._root)[0]

    @staticmethod
    def _key(prompt, k, P):
        return np.asarray(prompt[k * P:(k + 1) * P], np.int32).tobytes()

    def match(self, prompt, allow_full: bool = False) -> List[int]:
        """Longest cached page-prefix of ``prompt``, capped at
        ``(len(prompt) - 1) // page_size`` full pages (the engine must
        re-prefill at least the last prompt token — see module
        docstring).  ``allow_full=True`` lifts that cap to
        ``len(prompt) // page_size``: a preempted stream re-admitting
        feeds its NEXT token from its last committed one, so every row
        of its replay source is consumable KV and a full-cover hit
        skips prefill entirely.  Matched pages are increffed for the
        caller; the caller owns releasing them (decref) when the slot
        frees."""
        P = self._pool.page_size
        limit = (len(prompt) // P if allow_full
                 else (len(prompt) - 1) // P)
        pages, children = [], self._root
        self._clock += 1
        for k in range(limit):
            node = children.get(self._key(prompt, k, P))
            if node is None:
                break
            node.stamp = self._clock
            pages.append(node.page)
            children = node.children
        if pages:
            self._pool.incref(pages)
            self.hits += len(pages)
        return pages

    def insert(self, prompt, page_row, n_full: int) -> None:
        """Register the first ``n_full`` FULL prompt pages of a slot
        (``page_row[k]`` holds the page with tokens ``[k*P, (k+1)*P)``).
        Pages already present keep the existing physical page (two slots
        that prefilled the same prompt concurrently both offer a page;
        the first wins, the loser's stays private to its slot)."""
        P = self._pool.page_size
        n_full = min(int(n_full), len(prompt) // P)
        children, parent = self._root, None
        self._clock += 1
        for k in range(n_full):
            key = self._key(prompt, k, P)
            node = children.get(key)
            if node is None:
                node = _Node(key, page_row[k], parent)
                self._pool.incref(node.page)   # the cache's own reference
                children[key] = node
                self._nodes.append(node)
            node.stamp = self._clock
            children, parent = node.children, node

    def digests(self, limit: int = 64) -> List[int]:
        """Chain digests (see :func:`page_digests`) of up to ``limit``
        most-recently-touched nodes — the bounded ``prefix_digest``
        block the engine's ``/load`` report publishes.  A router hashes
        a prompt's page-aligned prefixes the same way and matches the
        deepest digest present here: that replica already holds those
        KV pages, so dispatching the request to it skips re-prefilling
        them (cache-affinity).  Bounded so a huge cache never bloats the
        capacity document; recency order keeps the entries that are
        still likely resident when the routed request lands.  Runs
        under the engine lock on every load probe (the router polls per
        dispatch), so it selects the top ``limit`` by stamp in
        O(n log limit) instead of fully sorting the node list."""
        top = heapq.nlargest(int(limit), self._nodes,
                             key=lambda nd: nd.stamp)
        return [nd.digest for nd in top]

    def remap_pages(self, remap: dict) -> int:
        """Rewrite cached physical page ids after a pool compaction
        (``remap`` maps old id -> new id, from
        :meth:`PagePool.apply_moves`).  Refcounts already moved with the
        pool commit; this keeps the radix tree pointing at the pages'
        new homes.  Returns how many nodes were rewritten."""
        n = 0
        for node in self._nodes:
            new = remap.get(node.page)
            if new is not None:
                node.page = int(new)
                n += 1
        return n

    def evict(self, n: int) -> int:
        """Free up to ``n`` pages by dropping LRU leaves nobody else
        references; returns how many were freed.  Dropping a leaf can
        expose its parent, so the scan loops until satisfied or stuck."""
        freed = 0
        while freed < n:
            victims = [nd for nd in self._nodes
                       if not nd.children
                       and self._pool.refcount(nd.page) == 1]
            if not victims:
                break
            victim = min(victims, key=lambda nd: nd.stamp)
            self._drop_node(victim)
            freed += 1
        return freed

    def _drop_node(self, node: _Node) -> None:
        siblings = node.parent.children if node.parent else self._root
        del siblings[node.key]
        self._nodes.remove(node)
        self._pool.decref(node.page)

    def drop(self) -> int:
        """Release every cached page (HBM reclaim / leak checks).  Pages
        still mapped by live slots stay allocated until those slots
        free."""
        n = len(self._nodes)
        for node in self._nodes:
            self._pool.decref(node.page)
        self._nodes.clear()
        self._root.clear()
        return n
