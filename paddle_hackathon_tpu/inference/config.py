"""Inference Config + pass pipeline.

Ref ``AnalysisConfig`` (``paddle/fluid/inference/api/analysis_config.cc``)
and ``PaddlePassBuilder`` (``api/paddle_pass_builder.h:38``). The reference
builds a list of named IR passes (fusions, memory optimisation, subgraph
engines) that rewrite the program before execution; on TPU, XLA performs
fusion/layout/memory planning during compilation, so passes here are
*program-level wrappers* applied by the Predictor at build time (dtype
autocast, buffer donation, input validation) rather than graph rewrites.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional


class PassBuilder:
    """Ordered, named pass pipeline (ref ``paddle_pass_builder.h:38``).

    A pass is ``name -> fn(predictor_build_ctx) -> None`` mutating the build
    context (compile options, wrappers). Users can delete/insert passes like
    the reference's ``config.pass_builder().DeletePass(...)``.
    """

    _registry: Dict[str, Callable] = {}

    def __init__(self, passes: Optional[List[str]] = None):
        self._passes: List[str] = list(passes) if passes is not None else [
            "donate_feed_buffers_pass",      # memory-optim: donate feed HBM
            "persistent_cache_pass",         # XLA compilation cache
            "resident_params_pass",          # pin weights on device
        ]

    @classmethod
    def register(cls, name: str):
        def deco(fn):
            cls._registry[name] = fn
            return fn
        return deco

    def all_passes(self) -> List[str]:
        return list(self._passes)

    def append_pass(self, name: str):
        self._passes.append(name)

    def insert_pass(self, idx: int, name: str):
        self._passes.insert(idx, name)

    def delete_pass(self, name: str):
        self._passes = [p for p in self._passes if p != name]

    def apply(self, ctx) -> None:
        for name in self._passes:
            fn = self._registry.get(name)
            if fn is not None:
                fn(ctx)


class Config:
    """Ref ``AnalysisConfig`` (``api/analysis_config.cc``).

    ``enable_use_gpu`` maps to TPU device selection; ``enable_memory_optim``
    maps to XLA buffer donation of feeds; ``set_optim_cache_dir`` maps to
    the XLA persistent compilation cache (the analog of caching the
    optimized program / TRT engines).
    """

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # path prefix (static artifact) or .pdmodel zip (jit artifact)
        self._prog_file = prog_file
        self._params_file = params_file
        self._use_device = "tpu"
        self._device_id = 0
        self._memory_optim = False
        self._ir_optim = True
        self._cpu_math_threads = 1
        self._optim_cache_dir: Optional[str] = None
        self._profile = False
        self._glog_info = True
        self._pass_builder = PassBuilder()
        self._exec_stream = None  # API-parity no-op: XLA orders execution

    # -- model location ----------------------------------------------------
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._prog_file = prog_file
        self._params_file = params_file

    def set_prog_file(self, f: str):
        self._prog_file = f

    def set_params_file(self, f: str):
        self._params_file = f

    def prog_file(self) -> Optional[str]:
        return self._prog_file

    def params_file(self) -> Optional[str]:
        return self._params_file

    # -- device ------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        # accepted for API parity; "gpu" means "the accelerator" = TPU here
        self._use_device = "tpu"
        self._device_id = device_id

    def enable_tpu(self, device_id: int = 0):
        self._use_device = "tpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._use_device = "cpu"

    def use_gpu(self) -> bool:
        return self._use_device == "tpu"

    def gpu_device_id(self) -> int:
        return self._device_id

    # -- optimisation knobs -------------------------------------------------
    def enable_memory_optim(self, x: bool = True):
        self._memory_optim = x

    def enable_memory_optim_(self):  # C++-style spelling
        self._memory_optim = True

    def memory_optim_enabled(self) -> bool:
        return self._memory_optim

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def ir_optim(self) -> bool:
        return self._ir_optim

    def set_cpu_math_library_num_threads(self, n: int):
        self._cpu_math_threads = n

    def cpu_math_library_num_threads(self) -> int:
        return self._cpu_math_threads

    def set_optim_cache_dir(self, d: str):
        self._optim_cache_dir = d

    # -- diagnostics ---------------------------------------------------------
    def enable_profile(self):
        self._profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def glog_info_disabled(self) -> bool:
        return not self._glog_info

    def pass_builder(self) -> PassBuilder:
        return self._pass_builder

    def summary(self) -> str:
        rows = [
            ("model_file", self._prog_file),
            ("params_file", self._params_file),
            ("device", f"{self._use_device}:{self._device_id}"),
            ("memory_optim", self._memory_optim),
            ("ir_optim", self._ir_optim),
            ("cpu_math_threads", self._cpu_math_threads),
            ("optim_cache_dir", self._optim_cache_dir),
            ("passes", ",".join(self._pass_builder.all_passes())),
        ]
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k.ljust(w)}  {v}" for k, v in rows)


# the reference aliases AnalysisConfig == Config in paddle.inference
AnalysisConfig = Config


# ---------------------------------------------------------------------------
# built-in passes
# ---------------------------------------------------------------------------

@PassBuilder.register("donate_feed_buffers_pass")
def _donate_feed_buffers_pass(ctx):
    """memory-optim analog of ``analysis/passes/memory_optimize_pass``:
    donate feed HBM buffers to the computation when memory optim is on."""
    if ctx.config.memory_optim_enabled():
        ctx.donate_feeds = True


@PassBuilder.register("persistent_cache_pass")
def _persistent_cache_pass(ctx):
    """Map ``set_optim_cache_dir`` onto the XLA persistent compilation
    cache — the analog of serializing the optimized program/TRT engine.

    The XLA cache is process-global in jax; the first predictor to set a
    dir wins, and a conflicting later dir is reported, not silently
    applied."""
    d = ctx.config._optim_cache_dir
    if not d:
        return
    import warnings

    import jax
    current = jax.config.jax_compilation_cache_dir
    if current and current != d:
        warnings.warn(
            f"XLA compilation cache already set to {current!r}; ignoring "
            f"optim_cache_dir {d!r} (the cache is process-global)")
        return
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:
        warnings.warn(f"could not enable XLA compilation cache at {d!r}: {e}")


@PassBuilder.register("resident_params_pass")
def _resident_params_pass(ctx):
    """Pin parameters on the target device once (ZeroCopy weights).
    Without this pass, weights stay on host and transfer every run."""
    ctx.resident_params = True
