"""Fault-tolerant serving fleet: a replica router over N engines.

Ref: the reference framework serves multi-rank inference through the
``fleet_executor`` actor pipeline (``dist_model.cc`` — a persistent
runtime fronting per-stage worker processes).  This module is the
TPU-native fleet half of that design, built on the groundwork the
observability layer shipped for it: each :class:`ServingEngine` replica
publishes a versioned ``/load`` capacity report (page-exact admission
headroom, rolling SLO percentiles, goodput, a ``prefix_digest``
cache-affinity block) and a ``/healthz`` liveness beacon — the
:class:`FleetRouter` is the thing that finally READS them.

Topology: in-process replica handles first.  A replica is anything
speaking the engine surface (``submit``/``load_report``/``drain``/
``shutdown`` + an ``engine_id``); the dispatch core is transport-
agnostic, so the multi-process deployment puts the same router behind
an HTTP shim polling ``/load`` instead of calling ``load_report()``
(docs/SERVING.md, "Serving fleet").

Dispatch (least-loaded + cache-affinity):

- candidates are live, non-draining replicas whose circuit breaker
  allows traffic and whose liveness beacon is not stale;
- among candidates whose ``admission.headroom_tokens`` admits the
  request RIGHT NOW, the deepest ``prefix_digest`` match wins (the
  replica already holds the prompt's prefix pages — repeat tenants land
  where their KV lives), then most headroom, then shortest queue;
- when nobody has headroom the request queues on the least-loaded
  replica (engines queue internally; FIFO admission bounds the wait);
- ``submit(session=)`` turns pin to the replica that served the last
  turn (it retains the conversation's KV pages for a suffix-cache
  resume); a draining/unhealthy pin target is skipped and the turn
  migrates — the pin is a fast path over the ``prefix_digest``
  affinity, never load-bearing for correctness.

Robustness is the headline:

- **deadlines** — ``submit(deadline_s=)`` is the request's TOTAL wall
  budget; the engine aborts it in-queue or mid-decode
  (``where="deadline"``), and a re-dispatch carries only the REMAINING
  budget.
- **bounded retry + backoff** — a failed placement (submit error,
  injected dispatch fault) retries against other replicas with
  exponential backoff; a replica DEATH re-dispatches its
  not-yet-started requests to a healthy replica.  A request that has
  streamed tokens is failed LOUDLY (:class:`StreamInterruptedError`
  naming the replica and the token count) — never silently retried,
  because a retry would duplicate output the caller already consumed.
- **circuit breaker** — consecutive failures (submit errors, load-probe
  errors, stale health) open a per-replica breaker; after a cool-down
  one half-open probe dispatch tests recovery (success closes, failure
  re-opens).
- **graceful drain** — :meth:`FleetRouter.drain` stops dispatching to a
  replica, lets its queued + inflight requests finish
  (``ServingEngine.drain``), then ``shutdown()`` — zero requests lost
  to a planned removal.
- **streaming backpressure** — :meth:`FleetRouter.submit_stream` yields
  tokens as the engine commits them through a BOUNDED queue: a slow
  consumer stalls that replica's decode loop (the engine delivers
  outside its lock), not the router or other requests.

Fault drills ride the ``PHT_FAULTS`` harness (observability/faults.py):
``fleet.dispatch`` fires per placement attempt,
``fleet.load_probe[<replica>]`` per capacity poll,
``fleet.stale_health[<replica>]`` inside the health gate, and the
engine's per-replica ``serving.tick[<engine_id>]`` kills ONE replica of
many deterministically — "kill a replica mid-flight" is a test, not a
hope (tests/test_fleet.py).

All shared router state is guarded by ``make_lock`` locks and declared
via ``share_object`` so the PHT009/PHT010 lint rules and the runtime
lockset sanitizer police it — this module is the first consumer the
race tooling was built for.

Fleet observability (docs/OBSERVABILITY.md, "Fleet telemetry"): every
dispatch mints a fleet-wide trace context (:meth:`FleetRequest.
trace_context` — fleet id, fleet rid, attempt ordinal; a plain dict
designed to ride an HTTP header later) that the replica stamps into its
lifecycle record and spans, while the router emits its own spans
(``fleet.route``/``fleet.dispatch``/``fleet.backoff``/
``fleet.failover``/``fleet.drain_migration``) on a per-fleet-request
lane — ``cross_stack.merge_traces(stitch_fleet=True)`` fuses both sides
into one swimlane per request.  :meth:`FleetRouter.load_report` /
``/fleet`` federates every replica's ``/load`` (version-gated, with
staleness ages), :meth:`FleetRouter.expose_text` federates their metric
text under a bounded ``replica=`` label, and a rules-driven watchdog
over the replicas' rolling SLO windows surfaces named degradation
reasons in :meth:`FleetRouter.health_report` / ``/healthz``.
"""

from __future__ import annotations

import itertools
import queue
import time
import warnings
import weakref
from typing import Dict, List, Optional

import numpy as np

from ..observability import faults as _faults
from ..observability import flight as _flight
from ..observability import metrics as _obs
from ..observability import tracing as _tr
from ..observability.sanitizers import make_lock, make_rlock, share_object
from .paged import page_digests
from .serving import (PRIORITY_RANK, DeadlineExceededError,
                      EngineDraining)

# placement-retry pacing per class: an interactive request backs off
# half as long between attempts (its SLO is the tightest), a batch
# request twice as long (it can wait; its retries must not crowd the
# dispatch path while the fleet is degraded)
_BACKOFF_FACTOR = {"interactive": 0.5, "default": 1.0, "batch": 2.0}

__all__ = ["FleetRouter", "FleetRequest", "CircuitBreaker",
           "NoReplicaAvailableError", "StreamInterruptedError",
           "pick_replica", "affinity_depth",
           "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN"]

_FLEET_IDS = itertools.count()
# fleet-wide request ids: process-wide like the engine's rids, but a
# SEPARATE sequence — one fleet request may burn several engine rids
# across failovers, and the merged-trace stitcher keys lanes on this
_FLEET_RIDS = itertools.count(1)
# chrome-trace lane base for router spans: engine spans lane on the
# (small-int) engine rid, router spans on _FLEET_LANE + fleet_rid so
# the two sequences never collide in an unstitched trace
_FLEET_LANE = 1 << 20

# session-pin map bound: pins past this evict oldest-first (the evicted
# conversation still routes right via prefix_digest affinity — a pin is
# a fast path, never load-bearing for correctness)
MAX_SESSION_PINS = 4096

# bound on the /debug/requests live-request table (the registry itself
# is weak — this caps only the rendered rows)
MAX_FORENSICS_ROWS = 256


class NoReplicaAvailableError(RuntimeError):
    """Every placement attempt failed: no live, non-draining,
    breaker-closed replica accepted the request within the retry
    budget.  Carries the last underlying failure as ``__cause__``."""


class StreamInterruptedError(RuntimeError):
    """A replica died AFTER streaming part of a request's output.  The
    router never silently re-dispatches a started stream — the caller
    has already consumed tokens, and a retry would duplicate them — so
    the failure is loud and names the replica and how far it got.  The
    replica's root cause rides ``__cause__``."""


# breaker states, exported as the fleet_breaker_state gauge values
BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN = 0, 1, 2


class CircuitBreaker:
    """Per-replica failure gate (closed → open → half-open → ...).

    Pure host state with the clock INJECTED at every transition, so the
    state machine unit-tests without sleeping.  The owner (the router)
    serializes access under its own lock.

    - ``failure_threshold`` consecutive failures open the breaker
      (dispatch stops);
    - after ``probe_interval_s`` the next :meth:`allows` turns it
      half-open and admits exactly ONE probe dispatch
      (:meth:`on_dispatch` marks it in flight — the owner must run the
      ``allows`` + ``on_dispatch`` pair as one atomic step under its
      lock at the dispatch decision, or two concurrent dispatches both
      read the unclaimed probe);
    - the probe's success closes the breaker (failure count reset), its
      failure re-opens it and restarts the cool-down."""

    __slots__ = ("failure_threshold", "probe_interval_s", "state",
                 "consecutive_failures", "_opened_at", "_probing")

    def __init__(self, failure_threshold: int = 3,
                 probe_interval_s: float = 1.0):
        self.failure_threshold = int(failure_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False

    def allows(self, now: float) -> bool:
        """May the router dispatch to this replica right now?  An open
        breaker past its cool-down transitions to half-open here (the
        decision point) and admits a single probe."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN:
            if now - self._opened_at < self.probe_interval_s:
                return False
            self.state = BREAKER_HALF_OPEN
            self._probing = False
        return not self._probing

    def on_dispatch(self) -> None:
        """The router is about to dispatch here; in half-open state
        that dispatch IS the probe — no second one until it resolves."""
        if self.state == BREAKER_HALF_OPEN:
            self._probing = True

    def record_failure(self, now: float) -> None:
        self.consecutive_failures += 1
        if (self.state == BREAKER_HALF_OPEN
                or self.consecutive_failures >= self.failure_threshold):
            self.state = BREAKER_OPEN
            self._opened_at = now
            self._probing = False

    def record_success(self) -> None:
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._probing = False


def affinity_depth(report: dict, digests: List[int]) -> int:
    """How many leading prompt pages this replica already holds: the
    deepest entry of ``digests`` (the prompt's chain digests from
    :func:`paged.page_digests`) present in the report's
    ``prefix_digest`` block.  0 when the replica publishes no block
    (dense replica) or nothing matches — chains hashed with a
    different page size simply never match (the running crc covers
    different byte spans), so a mixed fleet degrades to no affinity
    rather than wrong affinity."""
    pd = report.get("prefix_digest")
    if not pd or not digests:
        return 0
    have = pd.get("digests") or ()
    if not have:
        return 0
    have = set(have)
    depth = 0
    for k, d in enumerate(digests, 1):
        if d in have:
            depth = k
    return depth


def _queue_depth_for(report: dict, priority=None) -> int:
    """Queue depth AS SEEN BY a request of ``priority``: only classes
    scheduled at or before its own (the engine admits best effective
    class first), read from the ``queue.classes`` block.  Falls back to
    the total depth when no priority is given or the replica predates
    the block — an interactive queue starving behind a deep batch
    queue stops being invisible to least-loaded scoring."""
    q = report.get("queue") or {}
    classes = q.get("classes")
    if priority is None or not isinstance(classes, dict):
        return int(q.get("depth") or 0)
    r = PRIORITY_RANK.get(priority, 1)
    return sum(int(((classes.get(c) or {}).get("depth")) or 0)
               for c, rank in PRIORITY_RANK.items() if rank <= r)


def pick_replica(reports: Dict[str, dict], need: int,
                 digests: Optional[List[int]] = None,
                 exclude=(), priority=None,
                 explain: Optional[dict] = None) -> Optional[str]:
    """Pure dispatch scoring over ``/load`` reports (the router
    contract, docs/OBSERVABILITY.md "SLO telemetry and the /load
    report"); returns the chosen replica name, or None when no report
    is a candidate.

    Reading rules honored here: only ``version == 1`` documents count;
    ``draining`` replicas are never candidates; ``headroom_tokens`` is
    "would this request fit RIGHT NOW" as one comparison.  Scoring:
    among replicas whose headroom admits ``need``, deepest
    ``prefix_digest`` affinity match first (repeat tenants land on the
    replica already holding their pages), then most headroom, then
    shortest queue, then fewest active slots; when NOBODY has headroom
    the request queues on the least-loaded replica (shortest queue
    first — engines admit best-class-first, so the depth a request
    compares is only the classes scheduled at or before its own, via
    ``queue.classes`` when the replica publishes it).  Name
    order breaks remaining ties, so equal fleets dispatch
    deterministically.

    ``explain``, when a dict is passed, is filled in place with WHY the
    winner won — ``{"why": "affinity" | "headroom" | "queued_least_
    loaded", "affinity_depth": int, "headroom": int, "queue_depth":
    int}`` — the per-hop forensics record ``/debug/requests`` shows
    (an out-param so the scoring stays a pure single-return function
    for every existing caller)."""
    cands = []
    for name in sorted(reports):
        rep = reports[name]
        if name in exclude or not isinstance(rep, dict):
            continue
        if rep.get("version") != 1 or rep.get("draining"):
            continue
        adm = rep.get("admission") or {}
        head = int(adm.get("headroom_tokens") or 0)
        depth = _queue_depth_for(rep, priority)
        active = int((rep.get("slots") or {}).get("active") or 0)
        aff = affinity_depth(rep, digests) if digests else 0
        cands.append((name, head, depth, active, aff))
    if not cands:
        return None
    fits = [c for c in cands if c[1] >= need]
    if fits:
        best = min(fits, key=lambda c: (-c[4], -c[1], c[2], c[3], c[0]))
        why = "affinity" if best[4] else "headroom"
    else:
        best = min(cands, key=lambda c: (c[2], c[3], -c[1], c[0]))
        why = "queued_least_loaded"
    if explain is not None:
        explain.update(why=why, affinity_depth=best[4],
                       headroom=best[1], queue_depth=best[2])
    return best[0]


class _Replica:
    """Router-side record for one replica handle."""

    __slots__ = ("name", "handle", "breaker", "draining", "g_breaker",
                 "beacon", "last_report", "last_report_ts",
                 "version_warned")

    def __init__(self, name, handle, breaker, g_breaker):
        self.name = name
        self.handle = handle
        self.breaker = breaker
        self.draining = False
        self.g_breaker = g_breaker     # fleet_breaker_state child
        # liveness-beacon key: engines heartbeat under their OWN
        # engine_id, which may differ from the router-side name
        # (add_replica(name=...)) — keying the staleness gate on the
        # wrong string would silently disable it for that replica
        self.beacon = f"serving.{getattr(handle, 'engine_id', name)}"
        # last GOOD (version-1) /load report + its monotonic stamp: the
        # fleet load_report serves this with its staleness age when a
        # fresh probe fails, so a federated scrape shows "stale since"
        # instead of a hole
        self.last_report: Optional[dict] = None
        self.last_report_ts: Optional[float] = None
        # warn-once latch for an unknown /load envelope version
        self.version_warned = False


class FleetRequest:
    """Router-side request handle: re-pointable across replicas until
    the first token streams.

    Mirrors the engine :class:`Request` surface — ``wait(timeout)`` →
    done, ``result()`` raises-or-returns, ``.tokens``/``.done``/
    ``.error`` — plus fleet provenance: ``.replica`` (current
    placement) and ``.retries`` (re-dispatch count).  Terminal fleet
    failures (:class:`NoReplicaAvailableError`,
    :class:`StreamInterruptedError`) surface through ``.error`` /
    ``result()`` exactly like engine failures.

    Recovery runs lazily inside ``wait()``/the stream iterator: when
    the current engine request dies, the waiter calls the router back
    — the router re-dispatches a not-yet-started request (zero
    committed tokens) to another replica, and fails a started one
    loudly.  The per-request RLOCK is held across the whole recovery
    (decision + re-placement), so concurrent waiters serialize on it
    and exactly one performs the recovery — the rest observe the new
    generation when it releases.  (No ``__slots__``: the race
    sanitizer's ``share_object`` shim needs a swappable class
    layout.)"""

    def __init__(self, router, prompt, max_new_tokens, kw, deadline_s,
                 stream, session=None, priority=None):
        self._router = router
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self._kw = kw                      # sampling overrides
        self.session = session             # multi-turn KV session key
        self.priority = "default" if priority is None else priority
        self.deadline_s = deadline_s
        self._t_submit = time.perf_counter()
        # fleet-wide trace identity: survives failovers (each placement
        # burns a fresh engine rid; this one names the REQUEST) — the
        # lane key cross_stack's --stitch-fleet merges swimlanes on
        self.fleet_rid = next(_FLEET_RIDS)
        # dispatch attempt ordinal (every _try_dispatch bumps it,
        # including failover re-placements) — rides the trace context
        self._attempts = 0
        # per-hop forensics, appended under _lock per placement attempt:
        # which replica, why chosen, outcome/cause — the hop history
        # /debug/requests renders (bounded by the retry budget per
        # placement episode plus one failover marker per recovery)
        self.hops: List[dict] = []
        # queue-at-router span: submit() ends it at first successful
        # placement (or terminal failure) — router-side queueing +
        # retries are exactly the TTFT the replica cannot see
        self._span_route = _tr.start_span(
            "fleet.route", _tid=_FLEET_LANE + self.fleet_rid,
            fleet=router.fleet_id, fleet_rid=self.fleet_rid,
            priority=self.priority)
        # RLock: _recover holds it across _place, which re-acquires it
        # to install the new placement
        self._lock = make_rlock("fleet.request")
        self._req = None                   # current engine Request
        self._replica = None
        self._retries = 0
        self._failed: Optional[BaseException] = None
        self._stream_q = (queue.Queue(maxsize=router.stream_queue_tokens)
                          if stream else None)
        # consumer-gone latch: once set, on_token drops tokens instead
        # of backpressuring a tick loop nobody is reading from.
        # Written by the consumer/put-timeout, read by the engine's
        # driver thread — single aligned bool, declared atomic to the
        # race sanitizer below.
        self._closed = False
        share_object(self, f"fleet.request[{id(self)}]",
                     atomic=("_closed",))

    def trace_context(self) -> dict:
        """The fleet trace context this request's NEXT/current placement
        carries to its replica: ``{"fleet", "fleet_rid", "attempt"}``.
        A plain JSON-able dict by design — when replicas move behind
        HTTP this is the header payload, unchanged
        (docs/OBSERVABILITY.md, "Fleet telemetry")."""
        with self._lock:
            return {"fleet": self._router.fleet_id,
                    "fleet_rid": self.fleet_rid,
                    "attempt": self._attempts}

    # -- engine-Request-compatible surface --------------------------------
    def _settle(self):
        """Resolve any terminal-looking engine error through the
        router's recovery BEFORE exposing state: poll-style consumers
        (``done``/``error``/``result``) must get the same failover
        ``wait()``/``stream()`` perform, or a recoverable replica
        death would leak out as terminal to anyone who didn't block.
        Returns the settled ``(req, failed)`` pair."""
        while True:
            with self._lock:
                req, failed = self._req, self._failed
            if failed is not None or req is None or req.error is None:
                return req, failed
            # _recover serializes on the request lock and, by the time
            # it returns, has either recorded a terminal _failed or
            # installed a new placement — loop to look at that
            self._router._recover(self, req)

    @property
    def done(self) -> bool:
        req, failed = self._settle()
        if failed is not None:
            return True
        return bool(req is not None and req.done)

    @property
    def error(self) -> Optional[BaseException]:
        return self._settle()[1]

    @property
    def tokens(self) -> List[int]:
        with self._lock:
            req = self._req
        return list(req.tokens) if req is not None else []

    @property
    def rid(self):
        with self._lock:
            return self._req.rid if self._req is not None else None

    # provenance reads take the request lock like the rest of the
    # surface: a recovery on another thread re-points these mid-flight
    @property
    def replica(self) -> Optional[str]:
        with self._lock:
            return self._replica

    @property
    def retries(self) -> int:
        with self._lock:
            return self._retries

    def wait(self, timeout=None) -> bool:
        """Block until the request is terminal (finished, or failed
        beyond recovery); replica deaths are recovered HERE — the
        waiter is the thread with nothing better to do."""
        end = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                req, failed = self._req, self._failed
            if failed is not None:
                return True
            rem = None if end is None else max(0.0, end - time.monotonic())
            req._event.wait(rem)
            if not req._event.is_set():
                return False               # caller's timeout
            if req.error is None:
                return True                # finished clean
            self._router._recover(self, req)

    def result(self):
        """Full sequence (prompt + generated) or raise the terminal
        error — same contract as ``Request.result`` (recoverable
        replica deaths are settled through the router first)."""
        req, failed = self._settle()
        if failed is not None:
            raise failed
        if req is None:
            raise RuntimeError("request was never placed")
        return req.result()

    # -- streaming --------------------------------------------------------
    def _on_token(self, tok, gen):
        """Engine-side hook (replica driver thread, engine lock NOT
        held; ``_try_dispatch`` binds ``gen`` per placement).  The
        BOUNDED blocking put is the backpressure: a slow consumer
        stalls that replica's decode loop.  A consumer that stopped
        reading entirely (put times out / generator closed) flips
        ``_closed`` and the stream detaches — the engine finishes the
        request normally rather than wedging its tick loop.

        Entries are ``(generation, token-or-None)``: a failover leaves
        the dead placement's terminal ``None`` in the queue with NO
        ordering guarantee against the survivor's entries (two engine
        threads flush independently), so the consumer needs the tag to
        tell a stale terminal from the live generation's real end."""
        if self._closed:
            return
        try:
            self._stream_q.put((gen, tok),
                               timeout=self._router.stream_put_timeout_s)
        except queue.Full:
            self._closed = True

    def stream(self):
        """Generator yielding committed token ids as the fleet produces
        them; returns on clean finish, raises the terminal error
        (recovering replica deaths for not-yet-started requests along
        the way).  Closing the generator detaches the stream — the
        request keeps running, ``wait()``/``result()`` still work."""
        if self._stream_q is None:
            raise RuntimeError("not a streaming request; use "
                               "submit_stream()")
        try:
            while True:
                try:
                    gen, tok = self._stream_q.get(timeout=0.25)
                except queue.Empty:
                    if self._closed:
                        # the backpressure timeout detached this stream
                        # while the consumer was away: tokens (and the
                        # terminal) were DROPPED, so resuming the
                        # iterator can never deliver a complete stream
                        # — fail loudly; wait()/result() still return
                        # the full output
                        raise StreamInterruptedError(
                            "stream detached after the backpressure "
                            "put timeout (consumer stopped reading); "
                            "tokens were dropped — use wait()/result() "
                            "for the complete output")
                    with self._lock:
                        failed = self._failed
                    if failed is not None:
                        raise failed
                    continue
                if tok is not None:
                    yield tok
                    continue
                # a terminal: clean end, recoverable death, a loud
                # failure — or STALE (a dead generation's, possibly
                # enqueued out of order against the live placement's
                # entries; the live placement feeds the same queue)
                with self._lock:
                    req, failed, cur = self._req, self._failed, \
                        self._retries
                if failed is not None:
                    raise failed
                if gen != cur:
                    continue              # stale terminal: keep draining
                if req.error is None:
                    # the live generation's own terminal: its engine
                    # appends it under the lock that set done/error and
                    # flushes in order, so this really is the end
                    return
                self._router._recover(self, req)
                with self._lock:
                    failed = self._failed
                if failed is not None:
                    raise failed
                # recovered onto a fresh replica: keep draining the
                # same queue — the new placement feeds it
        finally:
            self._closed = True


class FleetRouter:
    """Health-driven replica router: least-loaded + cache-affinity
    dispatch, deadlines/retry/backoff, circuit breaking, graceful
    drain, per-token streaming (module docstring has the full design;
    docs/SERVING.md "Serving fleet" the operator view).

    Args:
      replicas: engine handles to front (``add_replica`` adds more
        later).  In-process ``ServingEngine`` objects, or anything
        speaking the same surface.
      max_retries: placement attempts per request beyond the first
        (dispatch failures back off exponentially from ``backoff_s`` by
        ``backoff_mult``).
      health_max_age_s: a replica whose liveness beacon
        (``serving.<engine_id>``) is older than this is treated as
        wedged (same rule as ``/healthz?max_age``); an ABSENT beacon is
        fine — idle engines drop theirs by design.
      breaker_failures / breaker_probe_interval_s: circuit-breaker
        threshold and cool-down (:class:`CircuitBreaker`).
      policy: ``"least_loaded"`` (default; headroom + affinity scoring
        via :func:`pick_replica`) or ``"round_robin"`` (rotation over
        healthy replicas — the affinity A/B baseline, not a production
        policy).
      stream_queue_tokens / stream_put_timeout_s: streaming
        backpressure bound and the consumer-gone detach timeout.
      watchdog_ttft_p99_s / watchdog_goodput_ratio / watchdog_skew:
        rules-driven degradation watchdog thresholds evaluated at every
        :meth:`load_report`/:meth:`health_report` over the replicas'
        rolling SLO windows — an interactive TTFT p99 past
        ``watchdog_ttft_p99_s``, a goodput ratio under
        ``watchdog_goodput_ratio`` right after preemptions grew, or a
        max/min load spread past ``watchdog_skew`` fires a named
        degradation (flight-recorder event on each transition, reason
        strings in ``/healthz``).
    """

    def __init__(self, replicas=(), *, max_retries: int = 2,
                 backoff_s: float = 0.02, backoff_mult: float = 2.0,
                 health_max_age_s: float = 10.0,
                 breaker_failures: int = 3,
                 breaker_probe_interval_s: float = 1.0,
                 policy: str = "least_loaded",
                 stream_queue_tokens: int = 64,
                 stream_put_timeout_s: float = 30.0,
                 watchdog_ttft_p99_s: float = 2.0,
                 watchdog_goodput_ratio: float = 0.5,
                 watchdog_skew: int = 64):
        if policy not in ("least_loaded", "round_robin"):
            raise ValueError(f"policy must be 'least_loaded' or "
                             f"'round_robin', got {policy!r}")
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_mult = float(backoff_mult)
        self.health_max_age_s = float(health_max_age_s)
        self.breaker_failures = int(breaker_failures)
        self.breaker_probe_interval_s = float(breaker_probe_interval_s)
        self.policy = policy
        self.stream_queue_tokens = int(stream_queue_tokens)
        self.stream_put_timeout_s = float(stream_put_timeout_s)
        self.watchdog_ttft_p99_s = float(watchdog_ttft_p99_s)
        self.watchdog_goodput_ratio = float(watchdog_goodput_ratio)
        self.watchdog_skew = int(watchdog_skew)

        self._lock = make_lock("fleet.router")
        self._replicas: Dict[str, _Replica] = {}
        self._rr = 0                      # round_robin rotation cursor
        # session stickiness: session key -> last replica that served a
        # turn of it (that replica retains the conversation's KV pages,
        # so a returning turn must land there to resume — the pin is a
        # fast path over the prefix_digest affinity scoring, which
        # still catches pin misses).  Bounded LRU: a chat fleet sees
        # unbounded session churn and the map must not grow with it.
        self._session_pins: Dict[str, str] = {}
        self.fleet_id = f"f{next(_FLEET_IDS)}"
        self._flight = _flight.get_flight_recorder()
        # live-request forensics registry: fleet_rid -> FleetRequest,
        # weak so a dropped handle vanishes from /debug/requests on its
        # own (mutation vs snapshot serialized under _lock, same
        # discipline as the tracing registries)
        self._requests: "weakref.WeakValueDictionary[int, FleetRequest]" \
            = weakref.WeakValueDictionary()
        # watchdog state: active rule key -> {"since", "reason"}; the
        # per-replica preemption counts from the previous evaluation
        # (the goodput rule fires on a crater RIGHT AFTER preemptions
        # grew, so it needs the delta)
        self._wd_state: Dict[str, dict] = {}
        self._wd_prev_preempt: Dict[str, int] = {}

        reg = self._registry = _obs.get_registry()
        lbl = {"fleet": self.fleet_id}
        self._fam_dispatch = reg.counter(
            "fleet_dispatch_total",
            "dispatch attempts by replica and outcome (ok / error / "
            "stale / probe_error / draining)")
        self._fam_retries = reg.counter(
            "fleet_retries_total",
            "request re-dispatches by reason (backoff_retry = placement "
            "retry within an episode, failover = replica-death "
            "re-dispatch)")
        self._fam_dispatch_s = reg.histogram(
            "fleet_dispatch_seconds",
            "submit-to-placed latency by outcome (hit = first attempt, "
            "retry = placed after backoff, failover = re-placed after a "
            "replica death)", unit="s")
        self._fam_vmismatch = reg.counter(
            "fleet_load_version_mismatch_total",
            "/load reports skipped for an unknown envelope version "
            "(deployment skew, not ill health: no breaker penalty)")
        self._fam_breaker = reg.gauge(
            "fleet_breaker_state",
            "per-replica circuit breaker (0 closed / 1 half-open / "
            "2 open)")
        self._g_draining = reg.gauge(
            "fleet_draining", "replicas currently draining").labels(**lbl)
        self._g_draining.set(0)
        self._g_skew = reg.gauge(
            "fleet_replica_skew",
            "max-min spread of per-replica load (queue depth + active "
            "slots) across live candidates").labels(**lbl)
        self._g_skew.set(0)

        for r in replicas:
            self.add_replica(r)
        # first consumer of the race tooling: every attr above is
        # mutated under _lock; the registry/flight handles hold their
        # own locks
        share_object(self, f"fleet.router[{self.fleet_id}]")
        _tr.register_introspection_source(self.fleet_id, self)
        _tr.register_fleet_source(self.fleet_id, self)

    # ------------------------------------------------------------------
    def add_replica(self, handle, name: Optional[str] = None) -> str:
        """Register a replica; returns its fleet name (the engine's
        ``engine_id`` unless overridden)."""
        name = name or getattr(handle, "engine_id", None)
        if name is None:
            raise ValueError("replica has no engine_id; pass name=")
        g = self._fam_breaker.labels(fleet=self.fleet_id, replica=name)
        g.set(BREAKER_CLOSED)
        rep = _Replica(name, handle,
                       CircuitBreaker(self.breaker_failures,
                                      self.breaker_probe_interval_s), g)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = rep
        return name

    def replica_names(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    # labelled-inc helpers: label VALUES arrive as parameters (replica
    # names and small outcome/reason enums — bounded), never loop
    # targets or request ids, which keeps PHT005 provably clean on the
    # dispatch hot path
    def _count(self, name: str, outcome: str) -> None:
        self._fam_dispatch.labels(
            fleet=self.fleet_id, replica=name, outcome=outcome).inc()

    def _count_retry(self, reason: str) -> None:
        self._fam_retries.labels(fleet=self.fleet_id, reason=reason).inc()

    def _observe_dispatch(self, outcome: str, seconds: float) -> None:
        self._fam_dispatch_s.labels(
            fleet=self.fleet_id, outcome=outcome).observe(seconds)

    def _version_mismatch(self, rep: _Replica, version) -> None:
        """An unknown /load envelope version: count it, warn ONCE per
        replica, and skip the report for scoring — deployment skew is
        not ill health, so no breaker penalty (a mixed-version rollout
        must not open breakers fleet-wide)."""
        self._fam_vmismatch.labels(
            fleet=self.fleet_id, replica=rep.name).inc()
        warn = False
        with self._lock:
            if not rep.version_warned:
                rep.version_warned = True
                warn = True
        if warn:
            warnings.warn(
                f"fleet {self.fleet_id}: replica {rep.name!r} publishes "
                f"/load envelope version {version!r} (expected 1); its "
                f"reports are skipped for dispatch scoring until it "
                f"speaks version 1", RuntimeWarning, stacklevel=3)

    # ------------------------------------------------------------------
    # health + capacity
    def _health_ok(self, rep: _Replica) -> bool:
        """Staleness gate, the ``/healthz?max_age`` rule: a beacon
        older than ``health_max_age_s`` means the replica's loop is
        wedged mid-work — don't feed it.  No beacon = idle or external
        replica = fine (idle engines drop theirs by design).  The
        per-replica ``fleet.stale_health[<name>]`` fault point makes
        "replica goes stale" a deterministic drill."""
        try:
            _faults.point(f"fleet.stale_health[{rep.name}]")
        except _faults.InjectedFault:
            return False
        age = _tr.beacon_ages().get(rep.beacon)
        return age is None or age <= self.health_max_age_s

    def _probe_load(self, rep: _Replica) -> Optional[dict]:
        """One capacity poll (the in-process ``/load`` read).  None on
        failure — the caller books it against the breaker."""
        _faults.point(f"fleet.load_probe[{rep.name}]")
        return rep.handle.load_report()

    # pht-lint: hot-root (fleet dispatch path — every request crosses it)
    def _candidates(self):
        """Health- and breaker-gated replicas with their fresh load
        reports.  Breaker decisions run under the router lock; the
        probes run OUTSIDE it (a replica's load_report takes the
        engine lock — never nest it under ours while other submitters
        wait)."""
        now = time.monotonic()
        with self._lock:
            reps = [r for r in self._replicas.values() if not r.draining]
            allowed = [r for r in reps if r.breaker.allows(now)]
            for r in allowed:
                r.g_breaker.set(r.breaker.state)
        out = []
        for rep in allowed:
            if not self._health_ok(rep):
                self._count(rep.name, "stale")
                self._record_failure(rep)
                continue
            try:
                report = self._probe_load(rep)
            except Exception:  # noqa: BLE001 — probe failure is data
                self._count(rep.name, "probe_error")
                self._record_failure(rep)
                continue
            if not isinstance(report, dict):
                # a non-dict "report" is a broken probe, not skew
                self._count(rep.name, "probe_error")
                self._record_failure(rep)
                continue
            if report.get("version") != 1:
                # the router contract: consumers must check version.
                # Unknown version = deployment skew — counted + warned
                # (once) and skipped for scoring; NOT a breaker failure
                self._version_mismatch(rep, report.get("version"))
                continue
            if report.get("draining"):
                # replica-side drain (someone called engine.drain()
                # directly): honor it without a breaker penalty.  The
                # record is HELD as draining — dispatch stops now, and
                # the operator completes the removal with
                # router.drain(name) (idempotent against an already-
                # draining engine), which also returns fleet_draining
                # to 0.  Auto-removing here would shutdown() an engine
                # the operator may still be watching drain.
                self._mark_draining(rep)
                continue
            with self._lock:
                # cache the good report: the fleet load_report serves
                # it with a staleness age when a later probe fails
                rep.last_report = report
                rep.last_report_ts = time.monotonic()
            out.append((rep, report))
        if len(out) >= 2:
            # max-min spread of (class-blind) load across the live
            # candidates — the skew series the one-hot-replica watchdog
            # rule and dashboards read.  Host arithmetic on reports
            # already in hand: no extra probe.
            loads = [_queue_depth_for(rep) +
                     int((rep.get("slots") or {}).get("active") or 0)
                     for _, rep in out]
            self._g_skew.set(max(loads) - min(loads))
        return out

    def _mark_draining(self, rep: _Replica) -> int:
        """Stop dispatching to ``rep`` and publish the fleet_draining
        gauge — the one place the draining flag is set (router drain,
        replica-side drain observed by a probe, EngineDraining on
        submit).  Returns how many session pins were migrated off the
        replica (the ``fleet.drain_migration`` span reports it)."""
        with self._lock:
            rep.draining = True
            self._g_draining.set(
                sum(r.draining for r in self._replicas.values()))
            # unpin its sessions NOW: the next turn of each migrates to
            # a survivor instead of queuing behind a drain (the drained
            # engine donates retained chains to its prefix cache, so a
            # same-replica re-admission would have replayed — but the
            # replica is leaving; the survivor re-prefilles, tokens
            # stay exact)
            stale_pins = [s for s, n in self._session_pins.items()
                          if n == rep.name]
            for sid in stale_pins:
                del self._session_pins[sid]
            return len(stale_pins)

    def _record_failure(self, rep: _Replica) -> None:
        with self._lock:
            rep.breaker.record_failure(time.monotonic())
            rep.g_breaker.set(rep.breaker.state)

    def _record_success(self, rep: _Replica) -> None:
        with self._lock:
            rep.breaker.record_success()
            rep.g_breaker.set(rep.breaker.state)

    # pht-lint: hot-root (fleet dispatch path)
    def _try_dispatch(self, freq: FleetRequest, exclude) -> bool:
        """One placement attempt; True when the request landed.  False
        = no candidate right now (retry may help); raises on a submit
        failure (booked against that replica's breaker) so the retry
        loop backs off before trying again.

        Observability bracket around :meth:`_dispatch_once` (the actual
        pick+submit): bumps the request's attempt ordinal, emits the
        ``fleet.dispatch`` span on the request's fleet lane, and
        appends the hop record (replica, why chosen, outcome, cause)
        the ``/debug/requests`` forensics table renders.  Both are
        host-side dict work — nothing here touches the device or mints
        a metric label from an id."""
        with freq._lock:
            freq._attempts += 1
            attempt = freq._attempts
        sp = _tr.start_span(
            "fleet.dispatch", _tid=_FLEET_LANE + freq.fleet_rid,
            fleet=self.fleet_id, fleet_rid=freq.fleet_rid,
            attempt=attempt)
        hop = {"attempt": attempt}
        try:
            placed = self._dispatch_once(freq, exclude, hop)
            hop.setdefault("outcome", "ok" if placed else "no_candidate")
            return placed
        except BaseException as e:
            hop.setdefault("outcome", "error")
            hop["cause"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            with freq._lock:
                freq.hops.append(hop)
            sp.end(**{k: v for k, v in hop.items() if k != "attempt"})

    # pht-lint: hot-root (fleet dispatch path)
    def _dispatch_once(self, freq: FleetRequest, exclude,
                       hop: dict) -> bool:
        """The pick + submit of one placement attempt (see
        :meth:`_try_dispatch` for the contract); fills ``hop`` with the
        forensics of what it did."""
        _faults.point("fleet.dispatch")
        cands = self._candidates()
        by_name = {rep.name: (rep, report) for rep, report in cands
                   if rep.name not in exclude}
        if not by_name:
            return False
        need = int(len(freq.prompt)) + freq.max_new_tokens
        name = None
        with self._lock:
            # session stickiness: resolve the pin and ACT on it under
            # ONE lock hold (no TOCTOU window against _mark_draining's
            # pin purge) — the pinned replica retains this
            # conversation's KV pages, so land there while it is a
            # live candidate; a draining/unhealthy/excluded pin fell
            # out of by_name above, so the turn migrates via the
            # normal pick below
            if freq.session is not None:
                pinned = self._session_pins.get(freq.session)
                if pinned is not None and pinned in by_name:
                    name = pinned
                    hop["why"] = "session_pin"
            if name is None and self.policy == "round_robin":
                names = sorted(by_name)
                name = names[self._rr % len(names)]
                self._rr += 1
                hop["why"] = "round_robin"
        if name is None:
            digests = None
            sizes = {(rep.get("prefix_digest") or {}).get("page_size")
                     for _, rep in by_name.values()}
            sizes.discard(None)
            if len(sizes) == 1:
                # one fleet-wide page size (the deployment norm): hash
                # the prompt once.  Mixed page sizes would need one
                # chain per size — affinity is skipped instead of
                # guessed (docs/SERVING.md).
                digests = page_digests(freq.prompt, sizes.pop())
            explain = {}
            name = pick_replica(
                {n: rep for n, (_, rep) in by_name.items()}, need,
                digests=digests, priority=freq.priority, explain=explain)
            if name is None:
                return False
            hop.update(explain)
        rep, _report = by_name[name]
        hop["replica"] = name
        deadline_rem = None
        if freq.deadline_s is not None:
            # the engine measures from ITS submit stamp: hand the
            # replica only what remains of the caller's total budget
            deadline_rem = freq.deadline_s - (time.perf_counter()
                                              - freq._t_submit)
            if deadline_rem <= 0:
                raise DeadlineExceededError(
                    f"request spent its whole deadline_s="
                    f"{freq.deadline_s} before a replica accepted it")
        with self._lock:
            # atomic re-check + probe claim: _candidates gated on
            # allows() BEFORE the unlocked health/probe window, so a
            # concurrent dispatch may have claimed the half-open probe
            # (or re-opened the breaker) since — "exactly one probe"
            # is enforced here, at the dispatch decision, under the
            # router lock
            if not rep.breaker.allows(time.monotonic()):
                hop["outcome"] = "breaker_lost_race"
                return False
            rep.breaker.on_dispatch()     # half-open: this IS the probe
        on_token = None
        if freq._stream_q is not None:
            # bind THIS placement's generation (the re-dispatch count:
            # _recover bumps it before re-placing, and the initial
            # placement happens-before any recovery) so the stream
            # consumer can tell a dead generation's stale terminal
            # from the live one's real end
            with freq._lock:
                gen = freq._retries

            def on_token(tok, _freq=freq, _gen=gen):
                _freq._on_token(tok, _gen)
        try:
            req = rep.handle.submit(
                freq.prompt, freq.max_new_tokens,
                deadline_s=deadline_rem,
                on_token=on_token,
                trace_ctx=freq.trace_context(),
                **freq._kw)
        except EngineDraining:
            # not a failure: mark and let the retry pick elsewhere
            self._mark_draining(rep)
            self._count(rep.name, "draining")
            hop["outcome"] = "draining"
            return False
        except Exception as e:
            self._count(rep.name, "error")
            self._record_failure(rep)
            # this episode must not re-pick the replica that just
            # refused (the breaker only opens after a streak): the
            # caller owns the mutable exclude set
            exclude.add(rep.name)
            raise e
        self._count(rep.name, "ok")
        self._record_success(rep)
        if freq.session is not None:
            with self._lock:
                # (re)pin last-wins; re-insert for LRU recency so hot
                # conversations survive the bound
                self._session_pins.pop(freq.session, None)
                self._session_pins[freq.session] = rep.name
                while len(self._session_pins) > MAX_SESSION_PINS:
                    self._session_pins.pop(
                        next(iter(self._session_pins)))
        with freq._lock:
            freq._req = req
            freq._replica = rep.name
        self._flight.record(
            "fleet", phase="dispatch", fleet=self.fleet_id,
            replica=rep.name, rid=req.rid, fleet_rid=freq.fleet_rid,
            retries=freq.retries)
        return True

    def _place(self, freq: FleetRequest, exclude=(),
               is_retry: bool = False) -> None:
        """Bounded retry-with-backoff around :meth:`_try_dispatch`.
        A replica whose submit raised is excluded for the REST of this
        placement episode (the breaker only opens after a streak — one
        episode must not burn its whole budget on one broken replica).
        When every replica is excluded the last attempts run
        un-excluded: with the fleet degraded that far, a replica that
        failed earlier in the episode beats refusing outright.  Raises
        :class:`NoReplicaAvailableError` (last failure as cause) when
        the budget is spent, :class:`DeadlineExceededError` when the
        caller's budget died first."""
        exclude = set(exclude)
        last_err = None
        t0 = time.perf_counter()
        delay = self.backoff_s * _BACKOFF_FACTOR.get(freq.priority, 1.0)
        for attempt in range(self.max_retries + 1):
            if attempt or is_retry:
                # reason taxonomy: a failover episode's first attempt is
                # the failover itself; later attempts (either episode
                # kind) are backoff retries
                self._count_retry("failover" if is_retry and not attempt
                                  else "backoff_retry")
            if attempt:
                bsp = _tr.start_span(
                    "fleet.backoff", _tid=_FLEET_LANE + freq.fleet_rid,
                    fleet=self.fleet_id, fleet_rid=freq.fleet_rid,
                    attempt=attempt, delay_s=delay)
                time.sleep(delay)
                bsp.end()
                delay *= self.backoff_mult
            if exclude >= set(self.replica_names()):
                exclude = set()     # whole fleet excluded: start over
            try:
                if self._try_dispatch(freq, exclude):
                    # episode latency by how hard placement was: first
                    # attempt = hit, placed after backoff = retry, any
                    # failover re-placement = failover
                    self._observe_dispatch(
                        "failover" if is_retry
                        else ("retry" if attempt else "hit"),
                        time.perf_counter() - t0)
                    return
            except DeadlineExceededError:
                raise
            except Exception as e:  # noqa: BLE001 — injected fault or
                last_err = e        # submit error: retry elsewhere
        raise NoReplicaAvailableError(
            f"no replica accepted the request after "
            f"{self.max_retries + 1} attempts "
            f"(replicas={self.replica_names()}, excluded={sorted(exclude)})"
        ) from last_err

    # ------------------------------------------------------------------
    # public submission surface
    def submit(self, prompt, max_new_tokens: int = 32, *,
               temperature=None, top_k=None, top_p=None,
               deadline_s=None, stream: bool = False,
               session=None, priority=None) -> FleetRequest:
        """Dispatch a request to the best replica (module docstring has
        the scoring); returns a :class:`FleetRequest`.  Raises
        :class:`NoReplicaAvailableError` when no replica accepts within
        the retry budget.

        ``session=`` names a multi-turn conversation: the key is handed
        through to the replica (``ServingEngine.submit(session=)``
        resumes the retained KV chain there) and the router PINS the
        session to the replica that served it, so the next turn lands
        where its pages live.  A pinned replica that is draining,
        unhealthy or breaker-open is simply skipped — the turn migrates
        (the survivor replays from its prefix cache at best, a cold
        prefill at worst; tokens stay exact either way) and the pin
        follows the new placement.

        ``priority=`` (interactive/default/batch) rides to the replica
        verbatim (``ServingEngine.submit(priority=)`` — class-ordered
        admission, preemption) and shapes the ROUTER side too: queue
        scoring counts only the classes scheduled at or before this
        one (``_queue_depth_for``), and placement-retry backoff scales
        by class (``_BACKOFF_FACTOR``) so a degraded fleet serves its
        tightest SLOs first."""
        if priority is not None and priority not in PRIORITY_RANK:
            raise ValueError(
                f"priority must be one of {sorted(PRIORITY_RANK)}, "
                f"got {priority!r}")
        freq = FleetRequest(
            self, prompt, max_new_tokens,
            {"temperature": temperature, "top_k": top_k, "top_p": top_p,
             "session": session, "priority": priority},
            None if deadline_s is None else float(deadline_s), stream,
            session=session, priority=priority)
        with self._lock:
            # forensics registry (weak): /debug/requests renders the
            # live handles' hop histories; a dropped handle vanishes
            self._requests[freq.fleet_rid] = freq
        try:
            self._place(freq)
        except BaseException as e:
            with freq._lock:
                freq._failed = e
            freq._span_route.end(error=type(e).__name__)
            raise
        freq._span_route.end(replica=freq.replica, retries=freq.retries)
        return freq

    def submit_stream(self, prompt, max_new_tokens: int = 32, **kw):
        """Per-token streaming: returns a generator yielding token ids
        as the serving engine commits them, through a bounded queue
        whose blocking put is the backpressure (a slow consumer stalls
        the producing replica's decode loop — never the router).  When
        you also need the request handle (``.retries``, ``.replica``),
        use ``submit(..., stream=True)`` and call ``.stream()`` on
        it — this helper is the common one-liner."""
        return self.submit(prompt, max_new_tokens, stream=True,
                           **kw).stream()

    def _recover(self, freq: FleetRequest, req) -> None:
        """A replica failed ``req`` (engine loop death, deadline, ...):
        decide the FleetRequest's fate.  Serialized per request by its
        lock; idempotent — late waiters observing an already-recovered
        generation return immediately.

        - deadline aborts are terminal (the caller's budget died, not
          the replica);
        - a STARTED stream (committed tokens exist) fails loudly
          (:class:`StreamInterruptedError`) — re-running it would
          duplicate output;
        - a not-yet-started request books a breaker failure against the
          dead replica and re-dispatches everywhere else."""
        with freq._lock:
            if freq._req is not req or freq._failed is not None:
                return                    # another waiter already did it
            if req.error is None:
                # nothing to recover: a stream consumer can get here on
                # a STALE queue terminal (the dead generation's fail-all
                # enqueued None, another waiter already re-placed the
                # request) — recovering a healthy placement would book a
                # breaker failure against a live replica and double-
                # place the request
                return
            failed_on = freq._replica
            if isinstance(req.error, DeadlineExceededError):
                freq._failed = req.error
                return
            if req.tokens:
                freq._failed = StreamInterruptedError(
                    f"replica {failed_on} died after streaming "
                    f"{len(req.tokens)} token(s) of this request; not "
                    f"re-dispatched — a retry would silently duplicate "
                    f"output the caller already consumed")
                freq._failed.__cause__ = req.error
                self._wake_stream(freq)
                return
            freq._retries += 1
            freq.hops.append({
                "attempt": freq._attempts, "outcome": "failover",
                "replica": failed_on,
                "cause": f"{type(req.error).__name__}: {req.error}"})
            # the replica broke a placed request: that is a health
            # event even though the submit itself succeeded earlier
            with self._lock:
                rep = self._replicas.get(failed_on)
            if rep is not None:
                self._record_failure(rep)
            self._flight.record(
                "fleet", phase="failover", fleet=self.fleet_id,
                replica=failed_on, rid=req.rid,
                fleet_rid=freq.fleet_rid)
            fsp = _tr.start_span(
                "fleet.failover", _tid=_FLEET_LANE + freq.fleet_rid,
                fleet=self.fleet_id, fleet_rid=freq.fleet_rid,
                from_replica=failed_on,
                cause=type(req.error).__name__)
            try:
                # re-dispatch AWAY from the dead replica.  Still inside
                # freq._lock (an RLock): concurrent waiters block here
                # until the new generation is installed, so exactly one
                # recovery runs.  Concurrent SUBMITS keep flowing —
                # they never touch this request's lock.
                self._place(freq, exclude=frozenset((failed_on,)),
                            is_retry=True)
            except BaseException as e:
                freq._failed = e
                self._wake_stream(freq)
                fsp.end(outcome="failed", error=type(e).__name__)
            else:
                fsp.end(outcome="re_placed", replica=freq._replica)

    @staticmethod
    def _wake_stream(freq: FleetRequest) -> None:
        """Wake a consumer blocked in the stream queue so it observes
        the terminal state now, not at its next poll timeout.  The
        generation-less tag never matches the consumer's current
        generation — the entry exists only to unblock the get(); the
        consumer reads the real terminal from ``_failed``."""
        if freq._stream_q is not None:
            try:
                freq._stream_q.put_nowait((None, None))
            except queue.Full:
                pass

    # ------------------------------------------------------------------
    # lifecycle
    def drain(self, name: str, timeout: float = 60.0) -> None:
        """Gracefully remove replica ``name``: stop dispatching to it
        immediately, let its queued + inflight requests finish
        (``handle.drain``), then ``handle.shutdown()`` and forget it —
        a planned removal loses zero requests (the fault-drill twin is
        the UNPLANNED removal, where failover does the work).

        A FAILED drain (backlog outlived ``timeout``, or the engine
        crashed mid-drain and raised) leaves the replica REGISTERED and
        marked draining: the router keeps refusing to dispatch there,
        the operator retries ``drain`` or escalates to the replica's
        own ``shutdown`` — silently forgetting a live engine would
        leave its daemon loop to die at interpreter exit mid-device
        call.  Success removes the replica and drops its labelled
        series (replica churn must not grow the registry forever)."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"no replica {name!r} "
                               f"(have {sorted(self._replicas)})")
        migrated = self._mark_draining(rep)
        self._flight.record("fleet", phase="drain", fleet=self.fleet_id,
                            replica=name)
        # drain-migration span on the router's own (fleet-id) lane: a
        # removal is fleet-scoped work, not one request's
        dsp = _tr.start_span("fleet.drain_migration", _tid=_FLEET_LANE,
                             fleet=self.fleet_id, replica=name,
                             migrated_pins=migrated)
        # ONE budget for the whole removal: shutdown gets what the
        # backlog drain left, not a fresh full timeout (an operator
        # watchdog sized to `timeout` must not fire mid-removal).  The
        # small floor lets the engine's loop-stopped poll run at least
        # once — after a completed drain it passes immediately.
        end = time.monotonic() + float(timeout)
        try:
            rep.handle.drain(timeout=timeout)
            rep.handle.shutdown(timeout=max(0.05, end - time.monotonic()))
        except BaseException as e:
            dsp.end(outcome="failed", error=type(e).__name__)
            raise
        with self._lock:
            self._replicas.pop(name, None)
            self._g_draining.set(
                sum(r.draining for r in self._replicas.values()))
        self._registry.drop_labels(fleet=self.fleet_id, replica=name)
        dsp.end(outcome="removed")

    def shutdown(self, timeout: float = 60.0) -> None:
        """Hard stop: shut every replica down (no drain — use
        :meth:`drain` per replica for graceful removal), unregister the
        router's introspection source and drop its labelled series
        (router churn must not grow the process registry forever)."""
        with self._lock:
            reps = list(self._replicas.values())
            self._replicas.clear()
            self._session_pins.clear()
            self._g_draining.set(0)
        for rep in reps:
            try:
                rep.handle.shutdown(timeout=timeout)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        _tr.unregister_introspection_source(self.fleet_id)
        _tr.unregister_fleet_source(self.fleet_id)
        self._registry.drop_labels(fleet=self.fleet_id)

    def introspect_requests(self) -> dict:
        """Router table for ``/debug/requests``: per-replica breaker
        state, draining flag, failure streak (snapshot under the
        router lock; host dicts only) — plus per-request hop forensics
        (which replica each attempt picked and why, each retry's
        cause) and the active watchdog degradations."""
        state_names = {BREAKER_CLOSED: "closed",
                       BREAKER_HALF_OPEN: "half_open",
                       BREAKER_OPEN: "open"}
        with self._lock:
            replicas = {
                name: {"breaker": state_names[r.breaker.state],
                       "consecutive_failures":
                           r.breaker.consecutive_failures,
                       "draining": r.draining}
                for name, r in self._replicas.items()}
            pins = len(self._session_pins)
            degraded = [dict(v, rule=k)
                        for k, v in sorted(self._wd_state.items())]
        return {"fleet": self.fleet_id, "policy": self.policy,
                "session_pins": pins, "replicas": replicas,
                "requests": self._forensics(), "watchdog": degraded}

    def _forensics(self, limit: int = MAX_FORENSICS_ROWS) -> dict:
        """Hop histories of the live fleet requests (weak registry:
        dropped handles have already vanished).  Reads raw fields under
        each request's lock — deliberately NOT ``_settle()``:
        introspection must never trigger a recovery/re-placement as a
        side effect of being looked at."""
        with self._lock:
            items = sorted(self._requests.items())[:limit]
        out = {}
        # no router lock held here: freq._lock nests router->request
        # nowhere (dispatch nests request->router), so taking it after
        # releasing ours keeps the lock order acyclic
        for frid, freq in items:
            with freq._lock:
                req = freq._req
                out[str(frid)] = {
                    "rid": req.rid if req is not None else None,
                    "replica": freq._replica,
                    "priority": freq.priority,
                    "retries": freq._retries,
                    "attempts": freq._attempts,
                    "done": bool(freq._failed is not None
                                 or (req is not None and req.done)),
                    "error": (type(freq._failed).__name__
                              if freq._failed is not None else None),
                    "hops": [dict(h) for h in freq.hops]}
        return out

    # ------------------------------------------------------------------
    # fleet telemetry: federation, health, watchdog
    # (docs/OBSERVABILITY.md, "Fleet telemetry")
    def load_report(self) -> dict:
        """The federated fleet capacity document — the ``/fleet``
        endpoint body (registered via ``tracing.register_fleet_source``
        at construction).  One fresh ``/load`` probe per replica
        (version-gated; an unknown version counts
        ``fleet_load_version_mismatch_total`` and the replica's entry
        carries no trusted fields), each entry labelled with its
        staleness: ``age_s`` is 0 for a fresh report, the cache age
        when the probe failed and the last GOOD report is served
        instead (``stale: true``).  Plus fleet-only aggregates:
        per-outcome dispatch percentiles, replica skew, merged SLO
        percentiles over in-process replicas' rolling windows, and the
        active watchdog degradations."""
        now = time.monotonic()
        ages = _tr.beacon_ages()
        with self._lock:
            reps = list(self._replicas.values())
        replicas = {}
        loads = []
        slo_wins: Dict[str, list] = {}
        for rep in reps:
            entry = {"draining": rep.draining,
                     "breaker": rep.breaker.state,
                     "beacon_age_s": (round(ages[rep.beacon], 3)
                                      if rep.beacon in ages else None)}
            report = None
            try:
                report = self._probe_load(rep)
            except Exception as e:  # noqa: BLE001 — probe failure is data
                entry["probe_error"] = f"{type(e).__name__}: {e}"
            if isinstance(report, dict) and report.get("version") == 1:
                with self._lock:
                    rep.last_report = report
                    rep.last_report_ts = now
                entry["report"] = report
                entry["age_s"] = 0.0
                entry["version_ok"] = True
                if not rep.draining:
                    loads.append(_queue_depth_for(report) + int(
                        (report.get("slots") or {}).get("active") or 0))
            else:
                if isinstance(report, dict):
                    self._version_mismatch(rep, report.get("version"))
                    entry["version_ok"] = False
                with self._lock:
                    stale, ts = rep.last_report, rep.last_report_ts
                if stale is not None:
                    # serve the cached good report WITH its age — a
                    # scrape shows "stale since", never silently-fresh
                    # numbers from a replica that stopped answering
                    entry["report"] = stale
                    entry["age_s"] = round(now - ts, 3)
                    entry["stale"] = True
            sw = getattr(rep.handle, "slo_windows", None)
            if callable(sw) and not entry.get("stale"):
                try:
                    for k, h in sw().items():
                        slo_wins.setdefault(k, []).append(h)
                except Exception:  # noqa: BLE001 — aggregation is best-effort
                    pass
            replicas[rep.name] = entry
        skew = (max(loads) - min(loads)) if len(loads) >= 2 else 0
        self._g_skew.set(skew)
        slo_merged = {}
        for k, wins in sorted(slo_wins.items()):
            try:
                slo_merged[k] = _obs.merged_percentiles(wins)
            except ValueError:
                # mixed bucket bounds across replicas: skip the merge
                # rather than publish a wrong percentile
                slo_merged[k] = None
        dispatch = {}
        for outcome in ("hit", "retry", "failover"):
            h = self._fam_dispatch_s.labels(
                fleet=self.fleet_id, outcome=outcome)
            if h.count:
                dispatch[outcome] = {
                    "count": int(h.count),
                    "p50_s": h.quantile(0.5), "p99_s": h.quantile(0.99)}
        return {"version": 1, "kind": "fleet", "fleet": self.fleet_id,
                "ts": time.time(), "policy": self.policy,
                "replicas": replicas, "replica_skew": skew,
                "dispatch": dispatch,
                "slo_merged": slo_merged or None,
                "watchdog": self._watchdog_eval(replicas)}

    def health_report(self) -> dict:
        """The fleet block of ``/healthz``: per-replica beacon ages
        sorted STALEST FIRST (the wedged replica is the first thing a
        probe reader sees), breaker/draining state, and the active
        watchdog degradation reasons.  ``ok`` is false when any beacon
        breaches ``health_max_age_s`` or a degradation is active — one
        fleet probe trips instead of N per-replica ones."""
        ages = _tr.beacon_ages()
        state_names = {BREAKER_CLOSED: "closed",
                       BREAKER_HALF_OPEN: "half_open",
                       BREAKER_OPEN: "open"}
        with self._lock:
            reps = [(r.name, r.beacon, r.draining, r.breaker.state)
                    for r in self._replicas.values()]
            degraded = [dict(v, rule=k)
                        for k, v in sorted(self._wd_state.items())]
        rows = []
        for name, beacon, draining, bstate in reps:
            rows.append({"replica": name,
                         "beacon_age_s": (round(ages[beacon], 3)
                                          if beacon in ages else None),
                         "draining": draining,
                         "breaker": state_names[bstate]})
        # stalest first; beacon-less replicas (idle engines drop
        # theirs by design) sort last — they are fine, not unknown
        rows.sort(key=lambda r: (r["beacon_age_s"] is not None,
                                 r["beacon_age_s"] or 0.0), reverse=True)
        stale = [r["replica"] for r in rows
                 if r["beacon_age_s"] is not None
                 and r["beacon_age_s"] > self.health_max_age_s]
        return {"fleet": self.fleet_id,
                "ok": not stale and not degraded,
                "stale_replicas": stale, "replicas": rows,
                "degraded": degraded}

    def _watchdog_eval(self, replicas: dict) -> list:
        """Evaluate the degradation rules over fresh per-replica
        entries (called from :meth:`load_report` with the probe results
        already in hand — the watchdog never adds probes).  Rule keys
        embed replica NAMES (bounded) and live in ``_wd_state``;
        each fired/cleared transition emits a flight-recorder event so
        the forensics timeline shows WHEN the fleet degraded.  Returns
        the active degradations, named."""
        now = time.time()
        fired: Dict[str, str] = {}
        loads = []
        for name, entry in sorted(replicas.items()):
            doc = entry.get("report")
            if not isinstance(doc, dict) or entry.get("stale"):
                continue
            slo_cls = (doc.get("slo") or {}).get("classes") or {}
            ttft = (slo_cls.get("interactive") or {}).get("ttft") or None
            if ttft and ttft.get("p99") is not None \
                    and ttft["p99"] > self.watchdog_ttft_p99_s:
                fired[f"ttft_p99[{name}]"] = (
                    f"interactive ttft p99 {ttft['p99']:.3f}s breaches "
                    f"{self.watchdog_ttft_p99_s}s on {name}")
            gp = (doc.get("goodput") or {}).get("ratio")
            pre = int(((doc.get("scheduler") or {})
                       .get("preemptions")) or 0)
            with self._lock:
                prev = self._wd_prev_preempt.get(name, 0)
                self._wd_prev_preempt[name] = pre
            if gp is not None and gp < self.watchdog_goodput_ratio \
                    and pre > prev:
                fired[f"goodput[{name}]"] = (
                    f"goodput ratio {gp:.2f} cratered below "
                    f"{self.watchdog_goodput_ratio} right after "
                    f"preemptions grew ({prev} -> {pre}) on {name}")
            if not entry.get("draining"):
                loads.append(_queue_depth_for(doc) + int(
                    (doc.get("slots") or {}).get("active") or 0))
        if len(loads) >= 2 and max(loads) - min(loads) > self.watchdog_skew:
            fired["replica_skew"] = (
                f"replica load spread {max(loads) - min(loads)} exceeds "
                f"{self.watchdog_skew} (one replica is hoarding or "
                f"starving)")
        events = []
        with self._lock:
            for key in sorted(fired):
                if key not in self._wd_state:
                    self._wd_state[key] = {"since": now,
                                           "reason": fired[key]}
                    events.append((key, "fired", fired[key]))
                else:
                    self._wd_state[key]["reason"] = fired[key]
            for key in [k for k in sorted(self._wd_state)
                        if k not in fired]:
                events.append((key, "cleared",
                               self._wd_state[key]["reason"]))
                del self._wd_state[key]
            active = [dict(v, rule=k)
                      for k, v in sorted(self._wd_state.items())]
        # flight records outside the router lock (locks are leaves)
        for key, state, reason in events:
            self._flight.record(
                "fleet", phase="watchdog", fleet=self.fleet_id,
                rule=key, state=state, reason=reason)
        return active

    def expose_text(self) -> str:
        """One federated Prometheus scrape for the whole fleet: every
        replica's series re-labelled ``replica="<name>"`` (bounded by
        fleet size — the PHT005 rule for the injected label) plus the
        router's own ``fleet_*`` series.  A replica handle exposing
        ``metrics_text()`` (the HTTP shim contract) is scraped through
        it; in-process engines are sliced out of the shared registry by
        their ``engine=`` label."""
        with self._lock:
            reps = [(r.name, r.handle) for r in self._replicas.values()]
        parts = {}
        for name, handle in reps:
            mt = getattr(handle, "metrics_text", None)
            try:
                if callable(mt):
                    parts[name] = mt()
                else:
                    parts[name] = self._registry.expose_text(
                        label_filter={
                            "engine": getattr(handle, "engine_id", name)})
            except Exception as e:  # noqa: BLE001 — scrape must not die
                parts[name] = (f"# replica scrape failed: "
                               f"{type(e).__name__}\n")
        return (_obs.federate_text(parts)
                + self._registry.expose_text(
                    label_filter={"fleet": self.fleet_id}))
