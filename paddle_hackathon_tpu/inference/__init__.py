"""paddle.inference — the deployment engine.

TPU-native equivalent of the reference's inference stack
(``paddle/fluid/inference``): ``AnalysisConfig``
(``api/analysis_config.cc``), ``AnalysisPredictor``
(``api/analysis_predictor.h:95``) with ``ZeroCopyRun`` (``:182``) and the
pass pipeline (``api/paddle_pass_builder.h:38``).

Architecture (TPU-first, not a port):
- the "optimized program" is a serialized **StableHLO** export (produced by
  ``paddle.static.save_inference_model`` or ``paddle.jit.save``) — XLA plays
  the role of the analysis passes + NaiveExecutor + TensorRT engine: graph
  fusion, memory planning and kernel selection all happen in one compile.
- ``Config`` carries the knobs the reference exposes (memory optim ↦ XLA
  buffer donation, optim cache dir ↦ XLA persistent compilation cache,
  cpu math threads, device selection).
- ``Predictor`` keeps parameters resident on the target device and runs the
  program through a cached ``jax.jit`` wrapper — the ZeroCopy analog: feeds
  are device_put once per ``copy_from_cpu``, outputs stay on device until
  ``copy_to_cpu``.
"""

from .config import Config, AnalysisConfig, PassBuilder
from .predictor import (Predictor, PredictorPool, Tensor as InferTensor,
                        create_predictor, get_version)
from .serving import (DeadlineExceededError, EngineDraining, Request,
                      ServingEngine)
# paged-KV host bookkeeping (ServingEngine(cache_mode="paged")): the
# page-pool allocator and the radix prefix cache
from .paged import PagePool, PrefixCache, page_digests, pages_for
# the serving fleet: health-driven replica router (failover, deadlines,
# retry/backoff, graceful drain, per-token streaming)
from .fleet import (CircuitBreaker, FleetRequest, FleetRouter,
                    NoReplicaAvailableError, StreamInterruptedError)
# speculative-decoding drafters (ServingEngine(spec_k=..., drafter=...) /
# GPTForCausalLM.generate(spec_k=...)) — re-exported here because serving
# is where users reach for them
from ..nn.decode import ModelDrafter, NGramDrafter

__all__ = [
    "Config", "AnalysisConfig", "PassBuilder", "Predictor", "PredictorPool",
    "InferTensor", "create_predictor", "get_version",
    "Request", "ServingEngine", "NGramDrafter", "ModelDrafter",
    "PagePool", "PrefixCache", "pages_for", "page_digests",
    "FleetRouter", "FleetRequest", "CircuitBreaker",
    "DeadlineExceededError", "EngineDraining",
    "NoReplicaAvailableError", "StreamInterruptedError",
]
