"""paddle.sysconfig (ref ``python/paddle/sysconfig.py``)."""

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory of the C headers / C ABI sources (ref sysconfig.get_include)."""
    return os.path.join(os.path.dirname(__file__), "native")


def get_lib():
    """Directory holding the built native library."""
    return os.path.join(os.path.dirname(__file__), "native", "_build")
