"""paddle.reader — generator-reader decorators
(ref ``python/paddle/reader/__init__.py``)."""

from .decorator import (  # noqa: F401
    cache, map_readers, buffered, compose, chain, shuffle, firstn,
    xmap_readers, multiprocess_reader, ComposeNotAligned,
)

__all__ = []
