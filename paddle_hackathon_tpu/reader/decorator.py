"""Reader decorators (ref ``python/paddle/reader/decorator.py:52-575``).

A *reader* is a zero-arg callable returning an iterable of samples; these
decorators compose readers: caching, mapping, buffering, shuffling,
chaining, composing, truncation and threaded/multiprocess fan-in.
"""

from __future__ import annotations

import itertools
import multiprocessing
import queue as _queue_mod
import random
from queue import Queue
from threading import Thread

__all__ = [
    'cache', 'map_readers', 'buffered', 'compose', 'chain', 'shuffle',
    'firstn', 'xmap_readers', 'multiprocess_reader', 'ComposeNotAligned',
]


def cache(reader):
    """Cache the reader's data in memory; later iterations replay it
    (ref ``decorator.py:52``)."""
    all_data = tuple(reader())

    def __impl__():
        for item in all_data:
            yield item

    return __impl__


def map_readers(func, *readers):
    """Map ``func`` over the zipped output of ``readers``
    (ref ``decorator.py:92``)."""

    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` samples
    (ref ``decorator.py:134``)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if len(buf) > 0:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers; outputs of the i-th come before the (i+1)-th
    (ref ``decorator.py:183``)."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples: outputs ``(1, 2, 3)`` and
    ``(4, 5)`` compose to ``(1, 2, 3, 4, 5)`` (ref ``decorator.py:248``).

    check_alignment=True (default) raises ComposeNotAligned when the
    readers have different lengths.
    """
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Read ahead into a bounded buffer on a worker thread
    (ref ``decorator.py:308``)."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Truncate the reader to the first ``n`` samples
    (ref ``decorator.py:367``)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


class XmapEndSignal:
    """Kept for API parity (some reference users type-check it); the
    futures-based pipeline below no longer passes end signals around."""


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map samples with ``process_num`` concurrent workers, optionally
    keeping input order.

    API/semantics of the reference ``decorator.py:412``; the machinery is
    a bounded sliding window of futures over a thread pool rather than
    the reference's reader-thread → in-queue → handler-threads →
    out-queue pipeline.  Ordering costs nothing here: submission order IS
    the window order, so ``order=True`` just drains the window FIFO
    (where the reference's handler threads busy-wait on a shared output
    counter), and ``order=False`` drains whatever finished first.
    Mapper exceptions surface to the consumer on ``result()`` instead of
    wedging a worker."""
    from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

    window = max(int(buffer_size), int(process_num), 1)

    def xreader():
        with ThreadPoolExecutor(max_workers=process_num) as pool:
            if order:
                from collections import deque
                inflight = deque()
                for sample in reader():
                    inflight.append(pool.submit(mapper, sample))
                    if len(inflight) >= window:
                        yield inflight.popleft().result()
                while inflight:
                    yield inflight.popleft().result()
            else:
                inflight = set()
                for sample in reader():
                    inflight.add(pool.submit(mapper, sample))
                    if len(inflight) >= window:
                        done, inflight = wait(
                            inflight, return_when=FIRST_COMPLETED)
                        for f in done:
                            yield f.result()
                while inflight:
                    done, inflight = wait(
                        inflight, return_when=FIRST_COMPLETED)
                    for f in done:
                        yield f.result()

    return xreader


# multiprocess_reader child→parent messages: tagged tuples, one writer per
# child process.  (tag, payload) with tags "item" / "done" / "error" —
# an exception's traceback text rides in "error" so the consumer can
# re-raise with context.
_MP_ITEM, _MP_DONE, _MP_ERROR = "item", "done", "error"

# Non-daemonic children (a reader may itself use multiprocessing) must
# not hang interpreter exit when a generator is abandoned mid-iteration:
# a child blocked on q.put() into a full queue would block
# multiprocessing's own atexit join forever.  This handler registers
# LATER than multiprocessing's (atexit is LIFO), so it terminates
# leftover children FIRST.
_mp_live_procs = []
_mp_atexit_registered = False


def _mp_terminate_leftovers():
    for p in list(_mp_live_procs):
        if p.is_alive():
            p.terminate()


def _mp_track(procs):
    global _mp_atexit_registered
    import atexit
    if not _mp_atexit_registered:
        atexit.register(_mp_terminate_leftovers)
        _mp_atexit_registered = True
    _mp_live_procs.extend(procs)


def _mp_untrack(procs):
    for p in procs:
        try:
            _mp_live_procs.remove(p)
        except ValueError:
            pass


def _mp_produce(reader, q):
    """Child-process body: stream one reader into the shared queue."""
    try:
        for sample in reader():
            if sample is None:
                raise ValueError(
                    "multiprocess_reader: readers must not yield None "
                    "(None is unrepresentable through the queue protocol)")
            q.put((_MP_ITEM, sample))
        q.put((_MP_DONE, None))
    except Exception as e:   # noqa: BLE001 — relayed to the parent
        import traceback
        q.put((_MP_ERROR, f"{type(e).__name__}: {e}\n"
                          f"{traceback.format_exc(limit=5)}"))


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fan-in multiple readers with one OS process each; samples
    interleave in arrival order.

    API of the reference ``decorator.py:505``; the wire protocol is
    tagged messages (see ``_mp_produce``) instead of the reference's
    None/empty-string sentinels, so a child exception carries its
    traceback to the parent's RuntimeError."""
    if len(readers) < 1:
        raise ValueError("readers must not be empty")

    def queue_reader():
        q = multiprocessing.Queue(queue_size)
        # non-daemonic: a reader may itself use multiprocessing (nested
        # pools); the finally below terminates+joins on any exit path,
        # and the atexit guard covers abandoned generators
        procs = [multiprocessing.Process(target=_mp_produce, args=(r, q))
                 for r in readers]
        _mp_track(procs)
        for p in procs:
            p.start()
        remaining = len(procs)
        try:
            while remaining:
                try:
                    tag, payload = q.get(timeout=60)
                except _queue_mod.Empty:
                    # slow readers are fine while their processes live;
                    # only a wedged pipeline (all workers dead, queue
                    # empty) is fatal
                    if any(p.is_alive() for p in procs):
                        continue
                    raise RuntimeError(
                        "multiprocess_reader: all reader processes exited "
                        "without finishing") from None
                if tag == _MP_DONE:
                    remaining -= 1
                elif tag == _MP_ERROR:
                    raise RuntimeError(
                        f"a reader subprocess raised:\n{payload}")
                else:
                    yield payload
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
                p.join()
            _mp_untrack(procs)

    # pipe-based variant behaves the same at this API level
    return queue_reader
