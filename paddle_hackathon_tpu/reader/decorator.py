"""Reader decorators (ref ``python/paddle/reader/decorator.py:52-575``).

A *reader* is a zero-arg callable returning an iterable of samples; these
decorators compose readers: caching, mapping, buffering, shuffling,
chaining, composing, truncation and threaded/multiprocess fan-in.
"""

from __future__ import annotations

import itertools
import multiprocessing
import queue as _queue_mod
import random
from queue import Queue
from threading import Thread

__all__ = [
    'cache', 'map_readers', 'buffered', 'compose', 'chain', 'shuffle',
    'firstn', 'xmap_readers', 'multiprocess_reader', 'ComposeNotAligned',
]


def cache(reader):
    """Cache the reader's data in memory; later iterations replay it
    (ref ``decorator.py:52``)."""
    all_data = tuple(reader())

    def __impl__():
        for item in all_data:
            yield item

    return __impl__


def map_readers(func, *readers):
    """Map ``func`` over the zipped output of ``readers``
    (ref ``decorator.py:92``)."""

    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` samples
    (ref ``decorator.py:134``)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if len(buf) > 0:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers; outputs of the i-th come before the (i+1)-th
    (ref ``decorator.py:183``)."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples: outputs ``(1, 2, 3)`` and
    ``(4, 5)`` compose to ``(1, 2, 3, 4, 5)`` (ref ``decorator.py:248``).

    check_alignment=True (default) raises ComposeNotAligned when the
    readers have different lengths.
    """
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Read ahead into a bounded buffer on a worker thread
    (ref ``decorator.py:308``)."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        t = Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Truncate the reader to the first ``n`` samples
    (ref ``decorator.py:367``)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


class XmapEndSignal:
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map samples with ``process_num`` worker threads, optionally keeping
    input order (ref ``decorator.py:412``)."""
    end = XmapEndSignal()

    def read_worker(reader, in_queue):
        for i in reader():
            in_queue.put(i)
        in_queue.put(end)

    def order_read_worker(reader, in_queue):
        for in_order, i in enumerate(reader()):
            in_queue.put((in_order, i))
        in_queue.put(end)

    def handle_worker(in_queue, out_queue, mapper):
        sample = in_queue.get()
        while not isinstance(sample, XmapEndSignal):
            out_queue.put(mapper(sample))
            sample = in_queue.get()
        in_queue.put(end)
        out_queue.put(end)

    def order_handle_worker(in_queue, out_queue, mapper, out_order):
        ins = in_queue.get()
        while not isinstance(ins, XmapEndSignal):
            order, sample = ins
            r = mapper(sample)
            # emit strictly in input order (reference busy-waits the same
            # way, decorator.py:459-464, but we sleep to avoid spinning)
            import time
            while order != out_order[0]:
                time.sleep(0.0005)
            out_queue.put(r)
            out_order[0] += 1
            ins = in_queue.get()
        in_queue.put(end)
        out_queue.put(end)

    def xreader():
        in_queue = Queue(buffer_size)
        out_queue = Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = Thread(target=target, args=(reader, in_queue))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_queue, out_queue, mapper, out_order) if order else \
            (in_queue, out_queue, mapper)
        workers = []
        for _ in range(process_num):
            w = Thread(target=target, args=args)
            w.daemon = True
            w.start()
            workers.append(w)
        finish = 0
        while finish < process_num:
            sample = out_queue.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fan-in multiple readers with one OS process each
    (ref ``decorator.py:505``). Samples interleave in arrival order."""
    if len(readers) < 1:
        raise ValueError("readers must not be empty")

    def _read_into_queue(reader, q):
        try:
            for sample in reader():
                if sample is None:
                    raise ValueError("sample has None")
                q.put(sample)
            q.put(None)
        except Exception:
            q.put("")
            raise

    def queue_reader():
        q = multiprocessing.Queue(queue_size)
        procs = []
        for reader in readers:
            p = multiprocessing.Process(target=_read_into_queue,
                                        args=(reader, q))
            p.start()
            procs.append(p)
        finish_num = 0
        while finish_num < len(readers):
            try:
                sample = q.get(timeout=60)
            except _queue_mod.Empty:
                # slow readers are fine while their processes live; only a
                # wedged pipeline (all workers dead, queue empty) is fatal
                if any(p.is_alive() for p in procs):
                    continue
                raise RuntimeError(
                    "multiprocess_reader: all reader processes exited "
                    "without finishing")
            if sample is None:
                finish_num += 1
            elif sample == "":
                raise RuntimeError("a reader subprocess raised an exception")
            else:
                yield sample
        for p in procs:
            p.join()

    # pipe-based variant behaves the same at this API level
    return queue_reader
