"""paddle.linalg namespace (ref ``python/paddle/linalg.py``) — a real
importable submodule so ``import paddle_hackathon_tpu.linalg`` works the way
``import paddle.linalg`` does, re-exporting the reference's export list."""

from .ops.linalg import (  # noqa: F401
    cholesky, norm, eig, cov, corrcoef, cond, matrix_power, solve,
    cholesky_solve, eigvals, multi_dot, matrix_rank, svd, eigvalsh, qr,
    lu, lu_unpack, eigh, det, slogdet, pinv, triangular_solve, lstsq,
)
from .ops.linalg import inverse as inv  # noqa: F401

__all__ = [
    'cholesky', 'norm', 'cond', 'cov', 'corrcoef', 'inv', 'eig', 'eigvals',
    'multi_dot', 'matrix_rank', 'svd', 'qr', 'lu', 'lu_unpack',
    'matrix_power', 'det', 'slogdet', 'eigh', 'eigvalsh', 'pinv', 'solve',
    'cholesky_solve', 'triangular_solve', 'lstsq',
]
