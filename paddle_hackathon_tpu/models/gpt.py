"""GPT-style decoder-only LM — the flagship model.

Capability target: the GPT-3 1.3B hybrid-parallel driver config (BASELINE.md)
and ERNIE-base pretraining throughput. Architecturally the paddle analog is
``PaddleNLP`` GPT + the reference's ``FusedMultiTransformer``
(``incubate/nn/layer/fused_transformer.py:914``) — here the transformer block
is built from this framework's layers, attention routes to the Pallas flash
kernel (``incubate/``), and parallelism is applied from outside via sharding
specs (see :func:`param_sharding_spec` and ``parallel/``): TP shards attention
heads / MLP, 'sp' shards the sequence axis, 'data'+'sharding' shard the batch
(DP x ZeRO), matching the reference's 4-D topology (``topology.py:52``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from .. import ops
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers.common import Dropout, Embedding, Linear
from ..nn.layers.norm import LayerNorm
from ..nn.parameter import ParamAttr


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    use_flash_attention: bool = None  # None = auto (seq-length heuristic)
    # MoE (GPT-MoE family): >0 replaces selected blocks' MLP with a
    # MoELayer whose expert dim shards over the 'ep' mesh axis.
    # moe_every_n selects WHICH blocks route: every n-th block (counting
    # from 1, so every_n=2 makes blocks 1, 3, 5, ... MoE and the rest
    # dense — the interleaved GPT-MoE layout); 1 = every block.
    moe_num_experts: int = 0
    moe_topk: int = 2
    moe_gate: str = "naive"
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    moe_every_n: int = 1
    # dispatch token-group size (None = auto; parallel/moe.py docstring)
    moe_group_size: Optional[int] = None

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size

    def block_uses_moe(self, layer_idx: int) -> bool:
        """Whether block ``layer_idx`` (0-based) routes through experts."""
        if self.moe_num_experts <= 0:
            return False
        n = max(1, int(self.moe_every_n))
        return (layer_idx + 1) % n == 0


_GPT_PRESETS = {
    # name: (layers, hidden, heads) — paddle fleetx GPT configs
    "gpt2-small-en": (12, 768, 12),         # 124M
    "gpt2-medium-en": (24, 1024, 16),       # 350M
    "gpt2-large-en": (36, 1280, 20),        # 774M
    "gpt3-1.3B-en": (24, 2048, 16),         # driver config #4
    "gpt3-2.7B-en": (32, 2560, 32),
    "gpt3-6.7B-en": (32, 4096, 32),
}


def gpt_config(name: str, **overrides) -> GPTConfig:
    layers, hidden, heads = _GPT_PRESETS[name]
    cfg = GPTConfig(num_layers=layers, hidden_size=hidden, num_heads=heads)
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


from ..nn.layers.transformer import SequenceParallelMixin


class GPTAttention(SequenceParallelMixin, Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        init = I.Normal(0.0, config.initializer_range)
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        self.qkv_proj = Linear(h, 3 * h, weight_attr=ParamAttr(initializer=init))
        self.out_proj = Linear(h, h, weight_attr=ParamAttr(initializer=init))
        self.dropout_p = config.attention_dropout_prob
        self.use_flash = config.use_flash_attention

    def _packed_flash_ok(self, qkv, s):
        from ..core import flags
        from ..incubate.nn.kernels import flash_attention_packed as _fap
        # mirror scaled_dot_product_attention's dispatch: explicit
        # use_flash=True forces flash at any supported length; None (auto)
        # applies the measured min-seqlen crossover
        if self.use_flash is False or not flags.flag("use_fused_kernels"):
            return False
        if self.use_flash is None and \
                s < flags.flag("flash_attention_min_seqlen"):
            return False
        from ..core.tensor import Tensor
        dtype = qkv._value.dtype if isinstance(qkv, Tensor) else qkv.dtype
        return _fap.supported(s, s, self.num_heads, self.head_dim, dtype)

    def forward(self, x, cache=None, cache_pos=None, page_table=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        if page_table is not None:
            # paged KV (serving engine cache_mode="paged"): ``cache`` is a
            # global page-pool pair ((num_pages, page_size, H, D)) shared
            # by every slot; ``page_table`` (B, pages_per_slot) maps each
            # slot's logical rows to physical pages and ``cache_pos`` is
            # the per-slot write offset.  Write-through-the-table, then
            # gather-attention (the Pallas decode kernel on TPU at width
            # 1, the exact-jnp reference otherwise) — same math, masking
            # and dtypes as the dense static-cache branch below, so paged
            # greedy decode is token-exact against it.
            if cache_pos is None:
                raise ValueError("page_table requires cache_pos")
            from ..incubate.nn.kernels import paged_attention as _pa
            qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
            q, k, v = ops.unstack(qkv, axis=2)

            def fn(qv, kv, vv, kp, vp, pt, pos):
                import jax.numpy as jnp
                pos = jnp.asarray(pos, jnp.int32)
                kp = _pa.paged_write(kp, kv, pt, pos)
                vp = _pa.paged_write(vp, vv, pt, pos)
                ctx = _pa.paged_attention(qv, kp, vp, pt, pos)
                return ctx.reshape(ctx.shape[0], ctx.shape[1], -1), kp, vp
            from ..core.autograd import apply_op
            out, new_k, new_v = apply_op(
                "gpt_paged_cache_attn", fn,
                [q, k, v, cache[0], cache[1], page_table, cache_pos],
                n_outputs=3)
            return self.out_proj(out), (new_k, new_v)
        if self._sp_enabled() and cache is None and cache_pos is None:
            # sequence-parallel training: the seq dim is sharded over the
            # 'sp' mesh axis; attention runs the ring/ulysses schedule
            # (parallel/sequence.py — flash-in-ring on TPU) against the
            # mesh enable_sequence_parallel() captured
            qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
            q, k, v = ops.unstack(qkv, axis=2)
            out = self._sp_attention(q, k, v, causal=True)
            out = ops.reshape(out, [b, s, h])
            return self.out_proj(out)
        if cache_pos is not None:
            # static-cache decode (jit-once generation): cache is a fixed
            # (B, max_len, H, D) pair — the train-time layout, so the
            # per-step cache write is an in-place contiguous
            # dynamic_update_slice (a head-major variant measured 68
            # us/step of full-cache copies when XLA lost the aliasing).
            # This call's k/v land at [cache_pos, cache_pos+s); queries
            # attend over cached positions <= their global position.
            # Compiled shapes never change across decode steps.
            import math as _math

            import jax
            import jax.numpy as jnp
            qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
            q, k, v = ops.unstack(qkv, axis=2)

            def fn(qv, kv, vv, kb, vb, pos):
                pos = jnp.asarray(pos, jnp.int32)
                if pos.ndim == 0:
                    zero = jnp.zeros((), jnp.int32)
                    start = (zero, pos, zero, zero)
                    kb = jax.lax.dynamic_update_slice(
                        kb, kv.astype(kb.dtype), start)
                    vb = jax.lax.dynamic_update_slice(
                        vb, vv.astype(vb.dtype), start)
                    qpos = pos + jnp.arange(qv.shape[1])[:, None]
                    kpos = jnp.arange(kb.shape[1])[None, :]
                    mask = (kpos <= qpos)[None, None]  # (1,1,s,T)
                else:
                    # per-slot positions (continuous-batching serving:
                    # each batch row is an independent request at its own
                    # cache depth). Statically unrolled per-row
                    # dynamic_update_slice, NOT vmap — vmapping the write
                    # over traced per-row offsets lowers to scatter,
                    # which measured ~3x the whole tick's decode time on
                    # TPU; a DUS chain stays an in-place slice write.
                    def rows_write(buf, upd):
                        zero = jnp.zeros((), jnp.int32)
                        for i in range(buf.shape[0]):
                            buf = jax.lax.dynamic_update_slice(
                                buf, upd[i:i + 1].astype(buf.dtype),
                                (jnp.asarray(i, jnp.int32), pos[i],
                                 zero, zero))
                        return buf
                    kb = rows_write(kb, kv)
                    vb = rows_write(vb, vv)
                    qpos = pos[:, None] + jnp.arange(qv.shape[1])[None, :]
                    kpos = jnp.arange(kb.shape[1])[None, None, :]
                    mask = (kpos <= qpos[..., None])[:, None]  # (b,1,s,T)
                # NOTE round-4: three Pallas fused-decode-attention
                # variants (3-D VPU, per-head MXU dots, head-batched
                # dot_general) measured 23/37/49 us/layer vs ~21 us for
                # this XLA composition at b8 T192 — kernel fixed costs
                # dominate at decode shapes; the composition stays
                # (BASELINE.md round-4 decode trace table)
                scale = 1.0 / _math.sqrt(qv.shape[-1])
                logits = jnp.einsum("bshe,bthe->bhst", qv,
                                    kb.astype(qv.dtype)) * scale
                logits = jnp.where(mask, logits,
                                   jnp.asarray(-1e30, logits.dtype))
                probs = jax.nn.softmax(logits, -1)
                ctx = jnp.einsum("bhst,bthe->bshe", probs,
                                 vb.astype(probs.dtype))
                return ctx.reshape(ctx.shape[0], ctx.shape[1], -1), kb, vb
            from ..core.autograd import apply_op
            out, new_k, new_v = apply_op(
                "gpt_static_cache_attn", fn,
                [q, k, v, cache[0], cache[1], cache_pos], n_outputs=3)
            return self.out_proj(out), (new_k, new_v)
        if cache is None and self._packed_flash_ok(qkv, s):
            # fast path: flash attention on the projection-native packed
            # layout — no head split/merge copies in HBM
            from ..incubate.nn.functional import flash_attention_qkv_packed
            out = flash_attention_qkv_packed(
                qkv, self.num_heads, causal=True,
                dropout_p=self.dropout_p if self.training else 0.0)
            return self.out_proj(out)
        qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unstack(qkv, axis=2)
        attn_mask = None
        is_causal = True
        if cache is not None:
            past_len = cache[0].shape[1]
            k = ops.concat([cache[0], k], axis=1)
            v = ops.concat([cache[1], v], axis=1)
            cache = (k, v)
            is_causal = False
            if s > 1:
                # chunked prefill: query position i (global past_len+i) may
                # attend to keys [0, past_len+i]
                import jax.numpy as jnp
                total = past_len + s
                causal = jnp.arange(total)[None, :] <= (
                    past_len + jnp.arange(s))[:, None]
                attn_mask = Tensor(
                    jnp.where(causal, 0.0, -1e30)[None, None].astype("float32"))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=is_causal,
            dropout_p=self.dropout_p if self.training else 0.0,
            training=self.training, use_flash=self.use_flash)
        out = ops.reshape(out, [b, s, h])
        out = self.out_proj(out)
        return out if cache is None else (out, cache)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.fc_in = Linear(config.hidden_size, config.ffn_size,
                            weight_attr=ParamAttr(initializer=init))
        self.fc_out = Linear(config.ffn_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTBlock(Layer):
    """Pre-LN transformer block (the fused_multi_transformer layout)."""

    def __init__(self, config: GPTConfig, use_moe: Optional[bool] = None):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size)
        # use_moe=None keeps the historical contract (any block of an MoE
        # config routes); GPTModel passes config.block_uses_moe(i) so
        # moe_every_n can interleave dense and routed blocks
        if (config.moe_num_experts > 0 if use_moe is None else use_moe):
            from ..parallel.moe import MoELayer
            self.mlp = MoELayer(
                config.hidden_size, config.ffn_size,
                config.moe_num_experts, gate=config.moe_gate,
                topk=config.moe_topk,
                capacity_factor=config.moe_capacity_factor,
                group_size=config.moe_group_size)
        else:
            self.mlp = GPTMLP(config)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, cache=None, cache_pos=None, page_table=None):
        attn_out = self.attn(self.ln_1(x), cache=cache, cache_pos=cache_pos,
                             page_table=page_table)
        if cache is not None:
            attn_out, cache = attn_out
        x = x + self.dropout(attn_out)
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x if cache is None else (x, cache)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.drop = Dropout(config.hidden_dropout_prob)
        self.blocks = LayerList([GPTBlock(config,
                                          use_moe=config.block_uses_moe(i))
                                 for i in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size)

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_pos=None, page_table=None):
        b, s = input_ids.shape
        # paged caches are (num_pages, page_size, H, D) pools — their
        # leading dims say nothing about past length; cache_pos does
        past_len = (caches[0][0].shape[1]
                    if caches is not None and page_table is None else 0)
        max_pos = self.wpe.weight.shape[0]
        if cache_pos is not None:
            # static-cache decode: positions come from the dynamic write
            # offset, not the (fixed, max_len) cache shape
            import jax.numpy as jnp
            from ..core.tensor import Tensor as _T
            pv = cache_pos._value if isinstance(cache_pos, _T) else cache_pos
            pv = jnp.asarray(pv, jnp.int32)
            if pv.ndim == 0:
                pos_idx = jnp.clip(
                    pv + jnp.arange(s, dtype=jnp.int32),
                    0, max_pos - 1)[None, :]
                pos_emb = self.wpe(_T(jnp.broadcast_to(pos_idx, (1, s))))
            else:
                # per-slot positions (serving engine): (B,) starts -> (B, s)
                pos_idx = jnp.clip(
                    pv[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :],
                    0, max_pos - 1)
                pos_emb = self.wpe(_T(pos_idx))
        elif position_ids is None and past_len + s <= max_pos:
            # Default positions are a contiguous arange, so the lookup is a
            # row slice of the weight — not a gather.  The slice's transpose
            # is a pad (identity when s == max_position_embeddings), which
            # keeps the wpe gradient off the batch-scatter path that GSPMD
            # can only reshard onto the ZeRO-3 param layout via involuntary
            # full rematerialization (spmd_partitioner.cc warning).
            pos_emb = ops.reshape(
                ops.slice(self.wpe.weight, axes=[0], starts=[past_len],
                          ends=[past_len + s]),
                [1, s, -1])
        else:
            if position_ids is None:
                # decode past max_position_embeddings: match gather's
                # clamped out-of-bounds behavior instead of crashing
                position_ids = ops.clip(
                    ops.arange(past_len, past_len + s, dtype="int32"),
                    0, max_pos - 1)
                position_ids = ops.reshape(position_ids, [1, s])
            pos_emb = self.wpe(position_ids)
        x = self.wte(input_ids) + pos_emb
        x = self.drop(x)
        new_caches = []
        for i, block in enumerate(self.blocks):
            if caches is None:
                x = block(x)
            else:
                x, c = block(x, cache=caches[i], cache_pos=cache_pos,
                             page_table=page_table)
                new_caches.append(c)
        x = self.ln_f(x)
        return x if caches is None else (x, new_caches)

    def gen_empty_caches(self, batch_size, dtype="float32"):
        from ..ops import creation
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        return [(creation.zeros([batch_size, 0, cfg.num_heads, head_dim], dtype),
                 creation.zeros([batch_size, 0, cfg.num_heads, head_dim], dtype))
                for _ in range(cfg.num_layers)]


class GPTForCausalLM(Layer):
    """LM head ties the embedding matrix (paddle GPTForPretraining)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_pos=None, page_table=None):
        hidden = self.gpt(input_ids, position_ids, caches=caches,
                          cache_pos=cache_pos, page_table=page_table)
        if caches is not None:
            hidden, caches = hidden
        logits = ops.matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        return logits if caches is None else (logits, caches)

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k: Optional[int] = None, jit_decode: bool = True,
                 top_p: Optional[float] = None, spec_k: int = 0,
                 drafter=None):
        """Greedy / top-k / nucleus sampling with a KV cache.

        ``jit_decode=True`` (default) preallocates a static
        (B, prompt+max_new, H, D) cache and compiles ONE fused program —
        prefill plus a ``lax.fori_loop`` over decode steps with in-jit
        sampling — cached per (batch, prompt, max_new, sampling) shape
        and reused across calls (the TPU-idiomatic serving loop; the
        growing-concat path recompiles every step because each step's
        cache shape is new, and pays a host round trip per token).

        ``spec_k > 0`` switches to speculative draft-and-verify decoding:
        a drafter (``drafter='ngram'`` prompt-lookup by default, or a
        small ``GPTForCausalLM``) proposes up to ``spec_k`` tokens per
        step and ONE widened forward verifies all of them, committing the
        longest prefix matching the target's greedy argmax — output is
        token-for-token identical to the non-speculative greedy path.
        Greedy only (``temperature`` must be 0.0).
        """
        from .. import ops as O

        self.eval()
        if spec_k:
            if temperature != 0.0:
                raise ValueError(
                    "spec_k requires temperature=0.0: speculative "
                    "acceptance matches the target's greedy argmax, so "
                    "only greedy decoding is exactly preserved")
            if not jit_decode:
                raise ValueError(
                    "spec_k requires jit_decode=True: the draft-and-"
                    "verify loop runs over the jitted static-cache "
                    "programs (the eager concat path has no verify step)")
            out = self._generate_spec(input_ids, max_new_tokens,
                                      int(spec_k), drafter)
            if out is not None:
                return out
            # pp mesh: no spec verify program — fall through to the
            # pipelined decode (same greedy tokens, just unsped)
        if jit_decode:
            return self._generate_static(input_ids, max_new_tokens,
                                         temperature, top_k, top_p)
        logits, caches = self(input_ids,
                              caches=self.gpt.gen_empty_caches(
                                  input_ids.shape[0]))
        out_ids = input_ids
        for _ in range(max_new_tokens):
            nxt = self._sample(logits._value[:, -1, :], temperature, top_k,
                               top_p=top_p)
            nxt_t = Tensor(nxt.astype(out_ids._value.dtype))
            out_ids = O.concat([out_ids, nxt_t], axis=1)
            logits, caches = self(nxt_t, caches=caches)
        return out_ids

    @staticmethod
    def _nucleus_mask(scaled, top_p):
        """Mask logits outside the nucleus: keep the smallest set of
        tokens whose probability mass reaches ``top_p`` (the top-1 token
        is always kept).  ``top_p`` is a scalar or a broadcastable (B, 1)
        per-row array."""
        import jax
        import jax.numpy as jnp
        probs = jax.nn.softmax(scaled, axis=-1)
        desc = -jnp.sort(-probs, axis=-1)
        csum = jnp.cumsum(desc, axis=-1)
        # token kept while the mass BEFORE it is still under p
        keep = (csum - desc) < jnp.maximum(top_p, 1e-9)
        kth = jnp.sum(keep, axis=-1, keepdims=True)  # >= 1 per row
        minp = jnp.take_along_axis(desc, kth - 1, axis=-1)
        return jnp.where(probs < minp, -1e30, scaled)

    @staticmethod
    def _sample(last, temperature, top_k, key=None, top_p=None):
        """Single owner of the sampling math (greedy / temperature /
        top-k / nucleus top-p) for every decode path.  ``key=None`` draws
        from the global RNG (eager concat path); the jit paths pass a
        traced key.

        Scalar mode (python-number ``temperature``): one config for the
        whole batch — the historical behavior, bit-for-bit.  Vector mode
        (array ``temperature``/``top_k``/``top_p`` of shape (B,)): each
        row samples under its own config — the serving engine's
        per-request sampling params; ``top_k=0`` / ``top_p=1.0`` disable
        the respective filter for that row, ``temperature=0`` makes the
        row greedy (identical argmax to the scalar greedy path: both
        argmax the same f32 ``logits / 1e-6``)."""
        import jax
        import jax.numpy as jnp

        from ..core import random as core_random
        last = last.astype(jnp.float32)
        if isinstance(temperature, (int, float)):
            last = last / max(temperature, 1e-6)
            if top_k is not None:
                cutoff = jax.lax.top_k(last, top_k)[0][:, -1:]
                last = jnp.where(last < cutoff, -1e30, last)
            if top_p is not None:
                last = GPTForCausalLM._nucleus_mask(last, float(top_p))
            if temperature == 0.0:
                return jnp.argmax(last, axis=-1, keepdims=True)
            if key is None:
                key = core_random.split_key()
            return jax.random.categorical(key, last)[:, None]
        temperature = jnp.asarray(temperature, jnp.float32)
        scaled = last / jnp.maximum(temperature, 1e-6)[:, None]
        greedy = jnp.argmax(scaled, axis=-1, keepdims=True)
        if top_k is not None:
            kk = jnp.asarray(top_k, jnp.int32)
            vocab = scaled.shape[-1]
            desc = -jnp.sort(-scaled, axis=-1)
            cut = jnp.take_along_axis(
                desc, jnp.clip(kk - 1, 0, vocab - 1)[:, None], axis=-1)
            scaled = jnp.where((kk > 0)[:, None] & (scaled < cut),
                               -1e30, scaled)
        if top_p is not None:
            scaled = GPTForCausalLM._nucleus_mask(
                scaled, jnp.asarray(top_p, jnp.float32)[:, None])
        if key is None:
            key = core_random.split_key()
        sampled = jax.random.categorical(key, scaled)[:, None]
        return jnp.where((temperature == 0.0)[:, None], greedy, sampled)

    def _param_mesh(self):
        """The device mesh the model's parameters are placed on, or None.

        When ``parallel.shard_params`` placed the weights (TP serving: a
        model that needs 'mp' to fit), the decode program composes the
        same mesh: KV caches shard their heads dim on 'mp', the batch on
        the data axes, and GSPMD inserts the in-decode collectives — the
        reference's ``fused_multi_transformer_op.cu`` runs its allreduce
        inside the fused decode step the same way (ring id argument), and
        ``DistModel`` serves multi-rank (``dist_model.cc``)."""
        from jax.sharding import NamedSharding
        sh = getattr(self.gpt.wte.weight._value, "sharding", None)
        if isinstance(sh, NamedSharding) and any(
                sh.mesh.shape.get(a, 1) > 1
                for a in ("mp", "dp", "sharding", "ep")):
            # 'ep' counts: the embedding itself is replicated over it,
            # but expert stacks shard on it, and decode must compose the
            # same mesh (batch over the data axes incl. 'ep') or GSPMD
            # gathers every expert to every rank per tick
            return sh.mesh
        return None

    def _generate_static(self, input_ids, max_new_tokens, temperature,
                         top_k, top_p=None):
        """One compiled program generates ALL tokens: prefill + a
        ``lax.fori_loop`` decode loop with in-jit sampling over a static
        KV cache.  No per-token host round trips — through the remote-chip
        tunnel a host-side sampling loop measures ~45 tok/s while this
        runs the whole generation on device."""
        import jax
        import jax.numpy as jnp

        from ..nn.layer import functional_call

        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if max_new_tokens <= 0:
            # prefill always samples one token, so the jitted program is
            # only built for >=1 new tokens; the eager path returns the
            # prompt unchanged for the same input
            return Tensor(ids)
        pp_mesh = None
        from ..parallel.api import get_mesh as _get_mesh
        amb = _get_mesh()
        if amb is not None and amb.shape.get("pp", 1) > 1:
            pp_mesh = amb
        if pp_mesh is not None:
            return self._generate_static_pp(ids, max_new_tokens,
                                            temperature, top_k, pp_mesh,
                                            top_p)
        b, prompt = ids.shape
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads
        max_len = prompt + max_new_tokens
        dtype = self.gpt.wte.weight._value.dtype
        caches = [(jnp.zeros((b, max_len, cfg.num_heads, head_dim), dtype),
                   jnp.zeros((b, max_len, cfg.num_heads, head_dim), dtype))
                  for _ in range(cfg.num_layers)]
        mesh = self._param_mesh()
        if mesh is not None:
            # TP/DP-sharded decode: caches shard heads on 'mp' (the qkv
            # projection's natural output sharding) and batch on the data
            # axes; ids likewise.  GSPMD then inserts the out_proj psum
            # and the vocab-parallel argmax/sample collectives inside the
            # one decode program.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.api import batch_spec, decode_cache_sharding
            cache_sh = decode_cache_sharding(mesh)
            bspec = batch_spec(mesh)
            bax = bspec[0] if len(bspec) else None
            caches = [(jax.device_put(k, cache_sh),
                       jax.device_put(v, cache_sh)) for k, v in caches]
            ids = jax.device_put(ids, NamedSharding(mesh, P(bax, None)))
        params, buffers = self.functional_state()
        # programs are cached per decode configuration — rebuilding the
        # closure every call would recompile every call (jax's jit cache
        # keys on function identity)
        cache_key = (b, prompt, max_new_tokens, temperature == 0.0,
                     float(temperature), top_k, top_p, str(dtype))

        def fwd(params, ids_in, caches, pos):
            return functional_call(
                self, params, (Tensor(ids_in),),
                kwargs={"caches": caches, "cache_pos": pos},
                buffers=buffers, training=False)

        return self._run_decode_program(
            cache_key, fwd, params, ids, caches, temperature, top_k,
            b, prompt, max_new_tokens, top_p=top_p)

    def _run_decode_program(self, cache_key, fwd, params, ids, caches,
                            temperature, top_k, b, prompt, max_new_tokens,
                            mesh=None, top_p=None):
        """Build-or-reuse the jitted decode program and invoke it —
        scaffolding shared by the single/mp path and the pp path (only
        ``fwd(params, ids_in, caches, pos) -> (logits, caches)``
        differs).  Prefill + ``lax.fori_loop`` token loop + in-jit
        sampling + in-program concat; the greedy key is created ONCE per
        program (the sampler never reads it — an eager key per call costs
        a full host round trip on remote-dispatch setups, ~100 ms through
        the axon tunnel; BASELINE round-4 decode notes)."""
        import contextlib

        import jax
        import jax.numpy as jnp

        from ..core import random as core_random

        greedy = temperature == 0.0
        gen_cache = self.__dict__.setdefault("_gen_program_cache", {})
        if cache_key not in gen_cache:
            def sample(last, key):
                return self._sample(last, temperature, top_k, key=key,
                                    top_p=top_p)

            @jax.jit
            def run(params, ids, caches, key):
                logits, caches_ = fwd(params, ids, caches,
                                      jnp.asarray(0, jnp.int32))
                nxt = sample(logits[:, -1, :],
                             jax.random.fold_in(key, 0)).astype(ids.dtype)
                outbuf = jnp.zeros((b, max_new_tokens), ids.dtype)
                outbuf = jax.lax.dynamic_update_slice(outbuf, nxt, (0, 0))

                def body(t, carry):
                    caches_, cur, outbuf = carry
                    logits, caches2 = fwd(params, cur, caches_,
                                          (prompt + t).astype(jnp.int32))
                    nx = sample(logits[:, -1, :],
                                jax.random.fold_in(key, t + 1)
                                ).astype(ids.dtype)
                    outbuf = jax.lax.dynamic_update_slice(
                        outbuf, nx, (jnp.asarray(0, jnp.int32), t + 1))
                    return caches2, nx, outbuf

                _, _, outbuf = jax.lax.fori_loop(
                    0, max_new_tokens - 1, body, (caches_, nxt, outbuf))
                # concat INSIDE the program: an eager concat after the
                # call would be one more host round trip per generate()
                return jnp.concatenate([ids, outbuf], axis=1)

            if len(gen_cache) >= 32:  # FIFO bound: variable-length serving
                gen_cache.pop(next(iter(gen_cache)))  # must not grow
            gen_cache[cache_key] = (run, jax.random.key(0) if greedy
                                    else None)
        run, greedy_key = gen_cache[cache_key]
        key = greedy_key if greedy else core_random.split_key()
        from ..core.jaxcompat import set_mesh as _set_mesh
        ctx = (_set_mesh(mesh) if mesh is not None
               else contextlib.nullcontext())
        with ctx:  # partial-manual shard_map (pp) needs the ambient mesh
            return Tensor(run(params, ids, caches, key))

    # pht-lint: hot-root (host draft-and-verify loop)
    def _generate_spec(self, input_ids, max_new_tokens, spec_k, drafter):
        """Speculative draft-and-verify greedy decoding (single-request
        path).  Two jitted programs — a prompt prefill and a (B, K+1)-wide
        VERIFY step that scores every proposal position in one forward
        over the static cache — plus a host loop that proposes drafts,
        accepts the longest argmax-matching prefix, and commits
        ``accepted+1`` tokens per round trip.  Rejected tails need no
        cache rollback: attention reads only ``kpos <= qpos`` and the
        next verify rewrites ``[length, length+K]``, so stale rows are
        never attended (the serving engine's tick shares this invariant).

        Output is bit-identical to ``_generate_static(temperature=0.0)``:
        both commit ``argmax(logits/1e-6)`` given the same committed
        prefix.  Returns None under a pp mesh (the caller falls back to
        the pipelined non-spec program — same tokens, no speedup).

        Acceptance counters land on ``self._last_spec_stats`` for the
        bench rows ({"proposed", "accepted", "ticks"})."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..nn.decode import accept_lengths, get_drafter
        from ..nn.layer import functional_call

        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if max_new_tokens <= 0:
            return Tensor(ids)
        from ..parallel.api import get_mesh as _get_mesh
        amb = _get_mesh()
        if amb is not None and amb.shape.get("pp", 1) > 1:
            return None
        b, prompt = ids.shape
        cfg = self.config
        K = int(spec_k)
        head_dim = cfg.hidden_size // cfg.num_heads
        # K extra rows: the last verify before a row finishes starts at
        # length prompt+max_new-1 and writes K+1 wide
        cache_len = prompt + max_new_tokens + K + 1
        dtype = self.gpt.wte.weight._value.dtype
        caches = [(jnp.zeros((b, cache_len, cfg.num_heads, head_dim), dtype),
                   jnp.zeros((b, cache_len, cfg.num_heads, head_dim), dtype))
                  for _ in range(cfg.num_layers)]
        mesh = self._param_mesh()
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.api import (batch_spec, decode_cache_sharding,
                                        token_batch_sharding)
            cache_sh = decode_cache_sharding(mesh)
            bspec = batch_spec(mesh)
            bax = bspec[0] if len(bspec) else None
            caches = [(jax.device_put(k, cache_sh),
                       jax.device_put(v, cache_sh)) for k, v in caches]
            ids = jax.device_put(ids, NamedSharding(mesh, P(bax, None)))
            tok_sh = token_batch_sharding(mesh)
        else:
            tok_sh = None
        params, buffers = self.functional_state()
        cache_key = ("spec", b, prompt, max_new_tokens, K, str(dtype))
        gen_cache = self.__dict__.setdefault("_gen_program_cache", {})
        if cache_key not in gen_cache:
            def prefill(params, ids_in, caches):
                logits, caches = functional_call(
                    self, params, (Tensor(ids_in),),
                    kwargs={"caches": caches,
                            "cache_pos": jnp.asarray(0, jnp.int32)},
                    buffers=buffers, training=False)
                nxt = self._sample(logits[:, -1, :], 0.0, None)
                return caches, nxt[:, 0].astype(jnp.int32)

            def verify(params, caches, toks, pos):
                logits, caches = functional_call(
                    self, params, (Tensor(toks),),
                    kwargs={"caches": caches, "cache_pos": pos},
                    buffers=buffers, training=False)
                out = self._sample(
                    logits.reshape(b * (K + 1), -1), 0.0, None)
                return caches, out[:, 0].reshape(b, K + 1).astype(jnp.int32)

            if len(gen_cache) >= 32:  # same FIFO bound as the fused loop
                gen_cache.pop(next(iter(gen_cache)))
            from ..observability.sanitizers import sanitize_donation
            gen_cache[cache_key] = (
                sanitize_donation(jax.jit(prefill, donate_argnums=(2,)),
                                  donate_argnums=(2,),
                                  site="gpt.spec_prefill"),
                sanitize_donation(jax.jit(verify, donate_argnums=(1,)),
                                  donate_argnums=(1,),
                                  site="gpt.spec_verify"))
        run_prefill, run_verify = gen_cache[cache_key]

        # resolve-once per (drafter, K): a ModelDrafter's jitted
        # ingest/propose programs live on the instance, so rebuilding it
        # every generate() would re-trace the draft model per call.  The
        # entry keeps a strong ref to the user's argument, so the id()
        # key cannot alias a recycled object.
        dcache = self.__dict__.setdefault("_spec_drafter_cache", {})
        entry = dcache.get((id(drafter), K))
        if entry is None or entry[0] is not drafter:
            if len(dcache) >= 8:
                dcache.pop(next(iter(dcache)))
            entry = (drafter, get_drafter(drafter, K))
            dcache[(id(drafter), K)] = entry
        dr = entry[1]
        dr.begin(b, cache_len)
        # explicit fetches (jax.device_get, not np.asarray-on-Array):
        # these are the loop's designed device->host syncs — one for the
        # prompt mirror, one per verify round trip — and the explicit
        # form is what the transfer-guard sanitizer whitelists
        np_ids = np.asarray(jax.device_get(ids), np.int32)
        dr.ingest(np_ids, np.zeros(b, np.int32),
                  np.full(b, prompt, np.int32))
        caches, tok0 = run_prefill(params, ids, caches)
        tok0 = jax.device_get(tok0)
        out = np.zeros((b, max_new_tokens), np.int32)
        out[:, 0] = tok0
        ngen = np.ones(b, np.int64)
        lengths = np.full(b, prompt, np.int32)  # committed cache rows
        last = tok0.copy()
        stats = {"proposed": 0, "accepted": 0, "ticks": 0}
        while (ngen < max_new_tokens).any():
            drafts, ndraft = dr.propose(last, lengths)
            ndraft = np.where(ngen >= max_new_tokens, 0, ndraft)
            toks = np.concatenate([last[:, None], drafts], axis=1)
            toks_j = jnp.asarray(toks)
            pos_j = jnp.asarray(lengths)
            if tok_sh is not None:
                toks_j = jax.device_put(toks_j, tok_sh)
                pos_j = jax.device_put(pos_j, tok_sh)
            caches, ver = run_verify(params, caches, toks_j, pos_j)
            ver = jax.device_get(ver)   # the round trip's designed fetch
            acc = accept_lengths(drafts, ndraft, ver)
            stats["ticks"] += 1
            ingest_nvalid = np.zeros(b, np.int32)
            old_lengths = lengths.copy()
            for i in range(b):
                if ngen[i] >= max_new_tokens:
                    continue  # frozen: re-verifies in place, commits nothing
                rem = max_new_tokens - int(ngen[i])
                # cap at the row's remaining budget: drafts past it are
                # discarded, and counting them would overstate the
                # acceptance rate the bench rows report
                stats["proposed"] += min(int(ndraft[i]), rem)
                stats["accepted"] += min(int(acc[i]), rem)
                take = min(int(acc[i]) + 1, rem)
                out[i, ngen[i]:ngen[i] + take] = ver[i, :take]
                ngen[i] += take
                if ngen[i] < max_new_tokens:
                    ingest_nvalid[i] = int(acc[i]) + 1
                    lengths[i] += int(acc[i]) + 1
                    last[i] = ver[i, int(acc[i])]
            if getattr(dr, "ingest_after_verify", True):
                # self-ingesting drafters already wrote these rows in
                # propose(); replaying them would recompute identical KV
                dr.ingest(toks, old_lengths, ingest_nvalid)
        self._last_spec_stats = stats
        return Tensor(jnp.concatenate(
            [ids, jnp.asarray(out).astype(ids.dtype)], axis=1))

    def _generate_static_pp(self, ids, max_new_tokens, temperature, top_k,
                            mesh, top_p=None):
        """Pipeline-sharded one-program decode: block params stacked over
        layers and sharded on 'pp'; each token crosses the stages via
        ``pipeline_decode_apply`` (masked sequential schedule), with the
        embedding/head replicated and 'mp'/'dp' riding GSPMD — the
        serving-side counterpart of the pp train step (the reference
        serves pipelined models through ``DistModel``'s per-stage
        processes, ``dist_model.cc``)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..nn.layer import functional_call
        from ..parallel.api import batch_spec, stack_block_params
        from ..parallel.pipeline import pipeline_decode_apply

        b, prompt = ids.shape
        cfg = self.config
        L = cfg.num_layers
        pp = mesh.shape.get("pp", 1)
        if L % pp:
            raise ValueError(
                f"num_layers={L} must divide evenly over pp={pp} stages "
                "for pipeline-sharded decode")
        head_dim = cfg.hidden_size // cfg.num_heads
        max_len = prompt + max_new_tokens
        max_pos = cfg.max_position_embeddings
        dtype = self.gpt.wte.weight._value.dtype
        prefix = self.pipeline_stage_spec()["block_prefix"]

        # stacking + placement reuse the train step's machinery and are
        # cached per (mesh, live param identity): fixed-weight serving
        # pays it once, a weight update (rebinding the tensors)
        # invalidates it.  Identity is tracked with WEAK refs — an id()
        # tuple alone could false-hit after CPython recycles a freed
        # array's address, while strong refs would pin the whole previous
        # parameter set in device memory until the next call
        import weakref
        live = tuple(p._value for _, p in self.named_parameters())
        mesh_key = tuple(sorted(mesh.shape.items()))
        placed = self.__dict__.setdefault("_pp_decode_param_cache", {})
        refs = placed.get("refs", ())
        hit = (placed.get("mesh") == mesh_key and len(refs) == len(live)
               and all(r() is v for r, v in zip(refs, live)))
        if not hit:
            placed["mesh"] = mesh_key
            placed["refs"] = tuple(weakref.ref(v) for v in live)
            placed["value"] = stack_block_params(
                self, mesh, param_sharding_spec, prefix, L)
        other, stacked = placed["value"]

        bspec = batch_spec(mesh)
        bax = bspec[0] if len(bspec) else None
        hax = "mp" if mesh.shape.get("mp", 1) > 1 else None
        cache_sh = NamedSharding(mesh, P("pp", bax, None, hax, None))
        zeros = jnp.zeros((L, b, max_len, cfg.num_heads, head_dim), dtype)
        caches = (jax.device_put(zeros, cache_sh),
                  jax.device_put(zeros, cache_sh))
        ids = jax.device_put(ids, NamedSharding(mesh, P(bax, None)))

        template = self.gpt.blocks[0]
        ln_f = self.gpt.ln_f

        def layer_step(lp, cache, x, pos):
            kc, vc = cache
            y, (nk, nv) = functional_call(
                template, lp, (Tensor(x),),
                kwargs={"cache": (kc, vc), "cache_pos": pos},
                training=False)
            return y, (nk, nv)

        def fwd(params, ids_in, caches, pos):
            other_p, stacked_p = params
            s = ids_in.shape[1]
            pos_idx = jnp.clip(pos + jnp.arange(s, dtype=jnp.int32),
                               0, max_pos - 1)
            x = (jnp.take(other_p["gpt.wte.weight"], ids_in, axis=0)
                 + jnp.take(other_p["gpt.wpe.weight"], pos_idx,
                            axis=0)[None])
            y, caches = pipeline_decode_apply(
                layer_step, stacked_p, caches, x, pos, mesh)
            xn = functional_call(
                ln_f, {"weight": other_p["gpt.ln_f.weight"],
                       "bias": other_p["gpt.ln_f.bias"]}, (Tensor(y),),
                training=False)
            logits = xn @ other_p["gpt.wte.weight"].T
            return logits, caches

        cache_key = ("pp", tuple(sorted(mesh.shape.items())), b, prompt,
                     max_new_tokens, temperature == 0.0,
                     float(temperature), top_k, top_p, str(dtype))
        return self._run_decode_program(
            cache_key, fwd, (other, stacked), ids, caches, temperature,
            top_k, b, prompt, max_new_tokens, mesh=mesh, top_p=top_p)

    def enable_sequence_parallel(self, axis: str = "sp", mesh=None,
                                 mode: str = "auto"):
        """Switch every attention layer to the ring/ulysses schedule over
        mesh axis ``axis`` (sequence/context parallelism inside the
        one-program train step — SURVEY §5.7, a capability the reference
        lacks). Delegates to the model-agnostic
        ``parallel.enable_sequence_parallel`` walker (any model whose
        attention carries ``supports_sequence_parallel`` works the same
        way); kept as a method for API compatibility.

        Persists on the model (like ``shard_params`` placement) until
        ``disable_sequence_parallel()``; ``make_sharded_train_step``
        enables/disables this automatically from the mesh's 'sp' axis."""
        from ..parallel.sequence import enable_sequence_parallel
        enable_sequence_parallel(self, axis, mesh, mode)

    def disable_sequence_parallel(self):
        from ..parallel.sequence import disable_sequence_parallel
        disable_sequence_parallel(self)

    def loss(self, input_ids, labels, position_ids=None):
        logits = self(input_ids, position_ids)
        return F.cross_entropy(
            ops.reshape(logits, [-1, self.config.vocab_size]),
            ops.reshape(labels, [-1]))

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def pipeline_stage_spec(self) -> dict:
        """Pipeline decomposition consumed by
        ``parallel.make_sharded_train_step`` when the mesh has a 'pp' axis
        (ref ``PipelineLayer`` segmentation ``parallel_layers/pp_layers.py:162``
        and ``PipelineParallel.forward_backward_pipeline``
        ``pipeline_parallel.py:82-152``).

        The embedding head/tail run replicated over 'pp' — the tied ``wte``
        is the reference's ``SharedLayerDesc`` (``pp_layers.py:77``); its
        cross-stage grad allreduce (``pipeline_parallel.py:149``) falls out
        of AD on the replicated placement.  The block stack is sharded over
        'pp' with a stacked leading layer dim.

        Returns dict with:
          block_prefix: param-name prefix of the per-layer block params
          num_layers:   total transformer layers
          pre_fn(params, buffers, ids, key)  -> (b, s, h) hidden states
          layer_fn(layer_params, x)          -> x  (one block, pure)
          post_fn(params, x, labels)         -> scalar loss
        Each mirrors the corresponding slice of ``GPTModel.forward`` /
        ``GPTForCausalLM.loss`` exactly (parity-tested vs the non-pp path).
        """
        import jax
        import jax.numpy as jnp
        from ..core import random as core_random
        from ..nn.layer import functional_call
        from ..nn.functional.loss import fused_softmax_ce_rows

        moe = self.config.moe_num_experts > 0
        if moe and max(1, int(self.config.moe_every_n)) != 1:
            # the pipeline schedule stacks ONE block template's params
            # over the layer dim (stack_block_params) — interleaved
            # dense/MoE blocks have different param sets and cannot
            # stack; ep/mp/dp compositions serve moe_every_n fine
            raise ValueError(
                "pipeline parallelism requires homogeneous blocks: "
                f"moe_every_n={self.config.moe_every_n} interleaves dense "
                "and MoE blocks — use moe_every_n=1 under a 'pp' mesh")
        template = self.gpt.blocks[0]
        drop = self.gpt.drop
        ln_f = self.gpt.ln_f
        vocab = self.config.vocab_size

        def pre_fn(params, buffers, ids, key):
            wte = params["gpt.wte.weight"]
            wpe = params["gpt.wpe.weight"]
            s = ids.shape[1]
            # row slice of wpe == GPTModel.forward's slice+reshape path
            pos = jax.lax.slice_in_dim(wpe, 0, s, axis=0)[None]
            x = jnp.take(wte, ids, axis=0) + pos
            with core_random.rng_scope(key):
                x = functional_call(drop, {}, (Tensor(x),))
            return x

        def layer_fn(layer_params, x):
            h = functional_call(template, layer_params, (Tensor(x),))
            if not moe:
                return h
            # MoE: the load-balance aux the forward just left on the
            # layer is consumed INSIDE the stage scan (pipeline_apply
            # accumulates it across layers/microbatches — the side
            # channel _collect_moe_aux reads cannot escape a lax.scan)
            aux = template.mlp.l_aux
            aux = aux._value if isinstance(aux, Tensor) else aux
            if aux is None:
                aux = jnp.zeros((), jnp.float32)
            return h, aux

        def post_fn(params, x, labels):
            xn = functional_call(
                ln_f, {"weight": params["gpt.ln_f.weight"],
                       "bias": params["gpt.ln_f.bias"]}, (Tensor(x),))
            logits = xn @ params["gpt.wte.weight"].T
            return jnp.mean(fused_softmax_ce_rows(
                logits.reshape(-1, vocab), labels.reshape(-1)))

        return {"block_prefix": "gpt.blocks.",
                "num_layers": self.config.num_layers,
                "pre_fn": pre_fn, "layer_fn": layer_fn, "post_fn": post_fn,
                "layer_aux": moe,
                "aux_weight": self.config.moe_aux_weight}


def param_sharding_spec(name: str, shape) -> tuple:
    """Named-axis PartitionSpec entries for each GPT parameter.

    The TP plan mirrors the reference's Megatron-style split
    (``parallel_layers/mp_layers.py``): qkv/fc_in are column-parallel (output
    dim on 'mp'), out_proj/fc_out are row-parallel (input dim on 'mp'), the
    embedding is vocab-parallel; everything else is replicated over 'mp'.
    ZeRO-3 ('sharding' axis) additionally shards the first remaining dim.
    Returns a tuple usable as jax.sharding.PartitionSpec(*spec).
    """
    if name.endswith(".weight_scale"):
        # weight-only quantization scales (nn/quant/weight_only.py): one
        # f32 per OUTPUT channel, so they follow the weight's out-feature
        # placement — sharded on 'mp' where the projection is column-
        # parallel, replicated where it is row-parallel.  Checked before
        # the weight rules: "qkv_proj.weight" substring-matches the
        # scale name too.
        if "qkv_proj." in name or "fc_in." in name:
            return ("mp",)
        return (None,)
    if "qkv_proj.weight" in name or "fc_in.weight" in name:
        return (None, "mp")       # (in, out): split output columns
    if "out_proj.weight" in name or "fc_out.weight" in name:
        return ("mp", None)       # split input rows
    if "qkv_proj.bias" in name or "fc_in.bias" in name:
        return ("mp",)
    # MoE expert stacks: expert dim on 'ep', hidden split on 'mp'
    # (same plan the MoELayer pspec annotations declare)
    if ".mlp.w1" in name:
        return ("ep", None, "mp")
    if ".mlp.b1" in name:
        return ("ep", "mp")
    if ".mlp.w2" in name:
        return ("ep", "mp", None)
    if ".mlp.b2" in name:
        return ("ep", None)
    if ".mlp.gate.weight" in name:
        return (None, None)       # router replicated
    if "wte.weight" in name:
        # vocab-parallel embedding (c_embedding); ZeRO-3 stacks 'sharding'
        # onto the vocab rows too — row-sharded gather/scatter-add partition
        # cleanly, while feature-dim sharding forces GSPMD to fully
        # rematerialize the batch-sharded cotangent (involuntary-remat).
        return (("mp", "sharding"), None)
    if "wpe.weight" in name:
        # ZeRO-3 would otherwise shard the *feature* dim; Shardy then
        # propagates that layout onto the batch-sharded activation cotangent
        # and GSPMD can only reach it via involuntary full rematerialization.
        # Row (position) sharding partitions the slice/pad grad path cleanly.
        return ("sharding", None)
    return tuple(None for _ in shape)
