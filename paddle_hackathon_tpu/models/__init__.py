"""Model zoo (ref ``python/paddle/vision/models`` + PaddleNLP GPT/ERNIE)."""

from .gpt import (GPTConfig, GPTForCausalLM, GPTModel, gpt_config,  # noqa: F401
                  param_sharding_spec)
