"""Model zoo (ref ``python/paddle/vision/models`` + PaddleNLP GPT/ERNIE)."""

from .gpt import (GPTConfig, GPTForCausalLM, GPTModel, gpt_config,  # noqa: F401
                  param_sharding_spec)
from .bert import (BertConfig, BertForPretraining,  # noqa: F401
                   BertForSequenceClassification, BertModel, ErnieModel,
                   ErnieForPretraining, ErnieForSequenceClassification,
                   bert_config, bert_mlm_pipeline, bert_param_sharding_spec,
                   ernie_config, masked_mlm_loss)
