"""PP-YOLOE-style anchor-free detector — the conv-heavy static-graph
driver config (BASELINE.md #5: "PP-YOLOE / PP-OCRv3-class detection model
via jit/static path").

Capability reference: PaddleDetection's PP-YOLOE (CSPResNet backbone,
CSPPAN neck, ET-head with distribution-focal regression); the reference
repo itself ships only the detection *ops* this builds on
(``python/paddle/vision/ops.py``: yolo-era ops, nms, deform conv). The
architecture here is a compact TPU-first re-design: plain SiLU ConvBN
blocks with CSP splits (XLA fuses BN+SiLU into the conv epilogue), an
anchor-free decoupled head, center-prior assignment for the training loss
(the task-aligned assigner simplified), and decode+NMS through
``vision.ops.nms`` for eval.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence

import jax.numpy as jnp

from .. import ops
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers.common import Linear
from ..nn.layers.conv import Conv2D
from ..nn.layers.norm import BatchNorm2D
from ..nn.parameter import ParamAttr

__all__ = ["PPYOLOEConfig", "CSPResNet", "CSPPAN", "PPYOLOEHead", "PPYOLOE",
           "ppyoloe_s"]


@dataclasses.dataclass
class PPYOLOEConfig:
    num_classes: int = 80
    # width/depth multipliers: (0.33, 0.50) ~ the "s" scale
    depth_mult: float = 0.33
    width_mult: float = 0.50
    reg_max: int = 16             # DFL distribution bins
    strides: Sequence[int] = (8, 16, 32)


def _c(ch, width_mult):
    return max(8, int(round(ch * width_mult / 8)) * 8)


def _n(n, depth_mult):
    return max(1, int(round(n * depth_mult)))


class ConvBNAct(Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.silu(x) if self.act else x


class CSPBlock(Layer):
    """CSP stage: split, run residual ConvBN bottlenecks on one branch,
    concat, fuse — the backbone building block."""

    def __init__(self, cin, cout, n_blocks):
        super().__init__()
        mid = cout // 2
        self.left = ConvBNAct(cin, mid, 1)
        self.right = ConvBNAct(cin, mid, 1)
        self.blocks = LayerList([
            LayerList([ConvBNAct(mid, mid, 3), ConvBNAct(mid, mid, 3)])
            for _ in range(n_blocks)])
        self.fuse = ConvBNAct(2 * mid, cout, 1)

    def forward(self, x):
        left = self.left(x)
        y = self.right(x)
        for pair in self.blocks:
            y = y + pair[1](pair[0](y))
        return self.fuse(ops.concat([left, y], axis=1))


class CSPResNet(Layer):
    """Backbone: stem + 3 downsampling CSP stages -> feature pyramid
    (strides 8/16/32)."""

    def __init__(self, cfg: PPYOLOEConfig):
        super().__init__()
        w, d = cfg.width_mult, cfg.depth_mult
        # stem downsamples 4x; each of the 3 stages downsamples 2x more,
        # so the pyramid comes out at true strides 8 / 16 / 32 (matching
        # PPYOLOEHead.strides — a 4th stage would shift them to 16/32/64)
        chs = [_c(64, w), _c(256, w), _c(512, w), _c(1024, w)]
        self.out_channels = chs[1:]
        self.stem = LayerList([
            ConvBNAct(3, chs[0] // 2, 3, stride=2),
            ConvBNAct(chs[0] // 2, chs[0], 3, stride=2),
        ])
        self.stages = LayerList()
        n = _n(3, d)
        for cin, cout in zip(chs[:-1], chs[1:]):
            self.stages.append(LayerList([
                ConvBNAct(cin, cout, 3, stride=2),
                CSPBlock(cout, cout, n),
            ]))

    def forward(self, x) -> List:
        for s in self.stem:
            x = s(x)
        feats = []
        for down, csp in self.stages:
            x = csp(down(x))
            feats.append(x)       # strides 8, 16, 32
        return feats


class CSPPAN(Layer):
    """PAN neck: top-down then bottom-up fusion with CSP blocks."""

    def __init__(self, in_channels, cfg: PPYOLOEConfig):
        super().__init__()
        self.reduces = LayerList([ConvBNAct(c, in_channels[0], 1)
                                  for c in in_channels])
        n = _n(3, cfg.depth_mult)
        c = in_channels[0]
        self.td_blocks = LayerList([CSPBlock(2 * c, c, n)
                                    for _ in in_channels[:-1]])
        self.downs = LayerList([ConvBNAct(c, c, 3, stride=2)
                                for _ in in_channels[:-1]])
        self.bu_blocks = LayerList([CSPBlock(2 * c, c, n)
                                    for _ in in_channels[:-1]])
        self.out_channels = [c] * len(in_channels)

    def forward(self, feats):
        feats = [r(f) for r, f in zip(self.reduces, feats)]
        # top-down: upsample deeper levels into shallower
        td = [feats[-1]]
        for i in range(len(feats) - 2, -1, -1):
            up = F.interpolate(td[0], scale_factor=2, mode="nearest")
            td.insert(0, self.td_blocks[i](ops.concat([feats[i], up],
                                                      axis=1)))
        # bottom-up
        outs = [td[0]]
        for i in range(len(feats) - 1):
            d = self.downs[i](outs[-1])
            outs.append(self.bu_blocks[i](ops.concat([d, td[i + 1]],
                                                     axis=1)))
        return outs


class PPYOLOEHead(Layer):
    """Decoupled anchor-free head: per-level cls logits and DFL-style
    distance distributions over ``reg_max`` bins per side."""

    def __init__(self, in_channels, cfg: PPYOLOEConfig):
        super().__init__()
        self.num_classes = cfg.num_classes
        self.reg_max = cfg.reg_max
        self.strides = tuple(cfg.strides)
        c = in_channels[0]
        self.cls_convs = LayerList([ConvBNAct(c, c, 3) for _ in in_channels])
        self.reg_convs = LayerList([ConvBNAct(c, c, 3) for _ in in_channels])
        prior = -math.log((1 - 0.01) / 0.01)   # focal-style cls bias prior
        self.cls_preds = LayerList([
            Conv2D(c, cfg.num_classes, 3, padding=1,
                   bias_attr=ParamAttr(initializer=I.Constant(prior)))
            for _ in in_channels])
        self.reg_preds = LayerList([
            Conv2D(c, 4 * cfg.reg_max, 3, padding=1) for _ in in_channels])
        self.proj = Tensor(jnp.arange(cfg.reg_max, dtype=jnp.float32))

    def forward(self, feats):
        cls_logits, reg_dists = [], []
        for i, f in enumerate(feats):
            cls_logits.append(self.cls_preds[i](self.cls_convs[i](f)))
            reg_dists.append(self.reg_preds[i](self.reg_convs[i](f)))
        return cls_logits, reg_dists

    def decode(self, cls_logits, reg_dists):
        """(B, sum HW, 4) boxes in input pixels + (B, sum HW, C) scores."""
        boxes, scores = [], []
        for lvl, (cl, rd) in enumerate(zip(cls_logits, reg_dists)):
            b, ncls, h, w = cl.shape
            stride = self.strides[lvl]
            clv = cl._value if isinstance(cl, Tensor) else cl
            rdv = rd._value if isinstance(rd, Tensor) else rd
            # distribution -> expected distances (l, t, r, b) per cell
            dist = rdv.reshape(b, 4, self.reg_max, h, w)
            dist = jnp.einsum("bkshw,s->bkhw", jnp.exp(
                dist - jnp.max(dist, axis=2, keepdims=True)) /
                jnp.sum(jnp.exp(dist - jnp.max(dist, axis=2, keepdims=True)),
                        axis=2, keepdims=True), self.proj._value)
            ys = (jnp.arange(h, dtype=jnp.float32) + 0.5)[:, None]
            xs = (jnp.arange(w, dtype=jnp.float32) + 0.5)[None, :]
            cx = jnp.broadcast_to(xs, (h, w)) * stride
            cy = jnp.broadcast_to(ys, (h, w)) * stride
            x1 = cx - dist[:, 0] * stride
            y1 = cy - dist[:, 1] * stride
            x2 = cx + dist[:, 2] * stride
            y2 = cy + dist[:, 3] * stride
            bx = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(b, h * w, 4)
            sc = F.sigmoid(Tensor(clv))._value.transpose(0, 2, 3, 1)
            boxes.append(bx)
            scores.append(sc.reshape(b, h * w, ncls))
        return (Tensor(jnp.concatenate(boxes, axis=1)),
                Tensor(jnp.concatenate(scores, axis=1)))


class PPYOLOE(Layer):
    """Backbone + neck + head; ``loss`` trains with center-prior
    assignment (BCE cls + L1 on DFL-expected distances); ``predict``
    decodes and NMS-filters."""

    def __init__(self, config: Optional[PPYOLOEConfig] = None):
        super().__init__()
        self.config = config or PPYOLOEConfig()
        self.backbone = CSPResNet(self.config)
        self.neck = CSPPAN(self.backbone.out_channels, self.config)
        self.head = PPYOLOEHead(self.neck.out_channels, self.config)

    def forward(self, images):
        return self.head(self.neck(self.backbone(images)))

    def loss(self, images, gt_boxes, gt_labels):
        """Simplified training objective: each gt is assigned to the cell
        containing its center at every level; cls BCE everywhere + L1
        distance regression on assigned cells.

        Targets are pure functions of the ground truth (no gradient), so
        they are built with raw jnp and enter the graph as constants; the
        prediction path stays in taped Tensor ops end-to-end so
        ``loss(...).backward()`` works in eager mode and the same code
        traces under jit (the driver's compiled-executor config)."""
        cls_logits, reg_dists = self(images)
        gb = gt_boxes._value if isinstance(gt_boxes, Tensor) else gt_boxes
        gl = gt_labels._value if isinstance(gt_labels, Tensor) else gt_labels
        total = None
        ncls = self.config.num_classes
        for lvl, (cl, rd) in enumerate(zip(cls_logits, reg_dists)):
            stride = self.head.strides[lvl]
            b, _, h, w = cl.shape
            # ---- constant targets (raw jnp; stop-gradient by design) ----
            cx = (gb[..., 0] + gb[..., 2]) / 2.0 / stride    # (B, G)
            cy = (gb[..., 1] + gb[..., 3]) / 2.0 / stride
            gi = jnp.clip(cx.astype(jnp.int32), 0, w - 1)
            gj = jnp.clip(cy.astype(jnp.int32), 0, h - 1)
            flat = gj * w + gi                               # (B, G)
            onehot = jnp.eye(ncls)[gl]                       # (B, G, C)
            valid = (gb[..., 2] > gb[..., 0])[..., None]     # (B, G, 1)
            tgt = jnp.clip(
                jnp.zeros((b, h * w, ncls)).at[
                    jnp.arange(b)[:, None], flat].add(onehot * valid),
                0.0, 1.0)
            gd = jnp.stack([
                cx - gi.astype(jnp.float32),                 # gt l in cells
                cy - gj.astype(jnp.float32),
                gi.astype(jnp.float32) + 1.0 - cx,
                gj.astype(jnp.float32) + 1.0 - cy,
            ], axis=1)                                       # (B, 4, G)
            tgt_t = Tensor(tgt)
            gd_t = Tensor(gd)
            valid_t = Tensor(jnp.transpose(
                jnp.broadcast_to(valid, valid.shape[:2] + (4,)),
                (0, 2, 1)).astype(jnp.float32))              # (B, 4, G)
            flat4 = Tensor(jnp.broadcast_to(flat[:, None, :],
                                            (b, 4, flat.shape[1])))
            denom = Tensor(jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0) * 4.0)
            # ---- taped prediction path ----
            logits = ops.reshape(ops.transpose(cl, [0, 2, 3, 1]),
                                 [b, h * w, ncls])
            cls_loss = F.binary_cross_entropy_with_logits(
                logits, tgt_t, reduction="mean")
            dist = ops.reshape(rd, [b, 4, self.config.reg_max, h * w])
            sm = F.softmax(dist, axis=2)
            proj = Tensor(self.head.proj._value.reshape(1, 1, -1, 1))
            exp_d = ops.sum(sm * proj, axis=2)               # (B, 4, HW)
            picked = ops.take_along_axis(exp_d, flat4, axis=2)
            reg_sum = ops.sum(ops.abs(picked - gd_t) * valid_t)
            reg_loss = reg_sum / denom
            lvl_loss = cls_loss + 0.5 * reg_loss
            total = lvl_loss if total is None else total + lvl_loss
        return total

    def predict(self, images, score_threshold=0.4, iou_threshold=0.5,
                top_k=100):
        """Decoded, NMS-filtered detections for a single image batch."""
        from ..vision.ops import nms
        self.eval()
        cls_logits, reg_dists = self(images)
        boxes, scores = self.head.decode(cls_logits, reg_dists)
        out = []
        bv, sv = boxes._value, scores._value
        for i in range(bv.shape[0]):
            conf = sv[i].max(-1)
            labels = sv[i].argmax(-1)
            m = conf >= score_threshold
            bi = Tensor(jnp.asarray(bv[i][m]))
            if bi.shape[0] == 0:
                out.append((bi, Tensor(jnp.zeros((0,))),
                            Tensor(jnp.zeros((0,), jnp.int32))))
                continue
            keep = nms(bi, iou_threshold, scores=Tensor(jnp.asarray(
                conf[m])), top_k=top_k)
            kv = keep._value if isinstance(keep, Tensor) else jnp.asarray(keep)
            out.append((Tensor(bv[i][m][kv]),
                        Tensor(conf[m][kv]),
                        Tensor(labels[m][kv].astype(jnp.int32))))
        return out


def ppyoloe_s(num_classes: int = 80) -> PPYOLOE:
    """The "s" scale (depth 0.33 / width 0.50)."""
    return PPYOLOE(PPYOLOEConfig(num_classes=num_classes))
