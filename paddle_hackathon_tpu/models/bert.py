"""BERT / ERNIE encoder family.

Capability target: the ERNIE/BERT-base pretraining driver config
(BASELINE.md, sharding_stage2) — the paddle analog is PaddleNLP
BERT/ERNIE over the reference's ``nn.TransformerEncoder``
(``python/paddle/nn/layer/transformer.py``) and fused attention
(``operators/fused/fused_attention_op.cu``). ERNIE shares the BERT
architecture (different pretraining corpus/presets), so ``ErnieModel`` is
a preset family over the same module.

TPU notes: attention routes to the Pallas flash kernel through
``F.scaled_dot_product_attention``; padding is a [b, 1, 1, s] additive mask
(static shapes — no ragged tensors); the TP plan in
:func:`bert_param_sharding_spec` mirrors the Megatron split used for GPT.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from .. import ops
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers.common import Dropout, Embedding, Linear
from ..nn.layers.norm import LayerNorm
from ..nn.parameter import ParamAttr


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    hidden_act: str = "gelu"
    # True (not None/auto) on purpose: the encoder's bidirectional
    # attention at its native 512 length measured FASTER on the flash
    # kernels than the XLA composition (packed 126.4k vs bshd-flash 123.8k
    # tok/s ERNIE-base MLM; the 1024 auto-crossover in core/flags.py was
    # measured for the causal GPT path). Set None for the auto heuristic
    # or False to force the XLA composition.
    use_flash_attention: bool = True

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size


_PRESETS = {
    # name: (layers, hidden, heads, vocab, type_vocab)
    "bert-base-uncased": (12, 768, 12, 30522, 2),
    "bert-large-uncased": (24, 1024, 16, 30522, 2),
    "bert-base-chinese": (12, 768, 12, 21128, 2),
    "ernie-1.0": (12, 768, 12, 18000, 2),
    "ernie-3.0-base-zh": (12, 768, 12, 40000, 4),
    "ernie-3.0-medium-zh": (6, 768, 12, 40000, 4),
}


def bert_config(name: str, **overrides) -> BertConfig:
    layers, hidden, heads, vocab, tv = _PRESETS[name]
    act = "relu" if name.startswith("ernie-1") else "gelu"
    cfg = BertConfig(num_layers=layers, hidden_size=hidden, num_heads=heads,
                     vocab_size=vocab, type_vocab_size=tv, hidden_act=act)
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


ernie_config = bert_config  # ERNIE presets share the module


class BertEmbeddings(Layer):
    """word + position + token-type embeddings, LN, dropout."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        attr = ParamAttr(initializer=init)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size, weight_attr=attr)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=attr)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=attr)
        self.layer_norm = LayerNorm(config.hidden_size)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = Tensor(
                jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s)))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((b, s), jnp.int32))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


from ..nn.layers.transformer import SequenceParallelMixin


class BertSelfAttention(SequenceParallelMixin, Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        init = I.Normal(0.0, config.initializer_range)
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        self.qkv_proj = Linear(h, 3 * h,
                               weight_attr=ParamAttr(initializer=init))
        self.out_proj = Linear(h, h, weight_attr=ParamAttr(initializer=init))
        self.dropout_p = config.attention_dropout_prob
        self.use_flash = config.use_flash_attention

    def _packed_flash_ok(self, qkv, s):
        from ..core import flags
        from ..core.tensor import Tensor
        from ..incubate.nn.kernels import flash_attention_packed as _fap
        if self.use_flash is False or not flags.flag("use_fused_kernels"):
            return False
        if self.use_flash is None and \
                s < flags.flag("flash_attention_min_seqlen"):
            return False
        dtype = qkv._value.dtype if isinstance(qkv, Tensor) else qkv.dtype
        return _fap.supported(s, s, self.num_heads, self.head_dim, dtype)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        if self._sp_enabled():
            # sequence-parallel training: seq sharded over 'sp', attention
            # runs ring/ulysses (bidirectional — causal=False)
            if attn_mask is not None:
                raise ValueError(
                    "attention masks are not supported under sequence "
                    "parallelism — pack sequences instead of padding")
            qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
            q, k, v = ops.unstack(qkv, axis=2)
            out = self._sp_attention(q, k, v, causal=False)
            return self.out_proj(ops.reshape(out, [b, s, h]))
        if attn_mask is None and self._packed_flash_ok(qkv, s):
            # projection-native packed flash path (no head split copies)
            from ..incubate.nn.functional import flash_attention_qkv_packed
            out = flash_attention_qkv_packed(
                qkv, self.num_heads, causal=False,
                dropout_p=self.dropout_p if self.training else 0.0)
            return self.out_proj(out)
        qkv = ops.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = ops.unstack(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.dropout_p if self.training else 0.0,
            training=self.training, use_flash=self.use_flash)
        return self.out_proj(ops.reshape(out, [b, s, h]))


class BertLayer(Layer):
    """Post-LN encoder block (the original BERT layout; the reference's
    ``TransformerEncoderLayer`` with normalize_before=False)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.attention = BertSelfAttention(config)
        self.ln_1 = LayerNorm(config.hidden_size)
        self.fc_in = Linear(config.hidden_size, config.ffn_size,
                            weight_attr=ParamAttr(initializer=init))
        self.fc_out = Linear(config.ffn_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.ln_2 = LayerNorm(config.hidden_size)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.act = config.hidden_act

    def forward(self, x, attn_mask=None):
        x = self.ln_1(x + self.dropout(self.attention(x, attn_mask)))
        h = self.fc_in(x)
        h = F.gelu(h, approximate=True) if self.act == "gelu" else F.relu(h)
        return self.ln_2(x + self.dropout(self.fc_out(h)))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden):
        return ops.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """Encoder trunk: embeddings -> N layers -> (sequence_output, pooled)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList([BertLayer(config)
                                  for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    @staticmethod
    def _additive_mask(attention_mask):
        """[b, s] 1/0 padding mask -> [b, 1, 1, s] additive bias."""
        if attention_mask is None:
            return None
        m = attention_mask._value if isinstance(attention_mask, Tensor) \
            else jnp.asarray(attention_mask)
        bias = jnp.where(m[:, None, None, :] > 0, 0.0, -1e30)
        return Tensor(bias.astype(jnp.float32))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = self._additive_mask(attention_mask)
        for layer in self.encoder:
            x = layer(x, mask)
        return x, self.pooler(x)


ErnieModel = BertModel


class BertLMPredictionHead(Layer):
    """MLM head: transform + decode tied to the word embedding."""

    def __init__(self, config: BertConfig, embedding_weights):
        super().__init__()
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = LayerNorm(config.hidden_size)
        self._decoder_weight = embedding_weights  # tied [vocab, hidden]
        from ..nn.parameter import create_parameter
        self.decoder_bias = create_parameter(
            [config.vocab_size], "float32",
            default_initializer=I.Constant(0.0))

    def forward(self, hidden, masked_positions=None):
        b, s, hh = hidden.shape
        if masked_positions is not None:
            # MLM pretraining path: decode ONLY the masked rows — flat
            # indices into (b*s) gathered BEFORE transform+decode, so the
            # 40k-vocab matmul runs on ~15% of positions (the reference's
            # masked_positions head contract, e.g.
            # auto_parallel_gpt_model.py:929 and PaddleNLP's pretraining
            # heads; round-4 ERNIE trace: the full-logits trio was 33 ms
            # of a 204 ms step)
            hidden = ops.gather(ops.reshape(hidden, [-1, hh]),
                                masked_positions)            # (K, hh)
        h = self.layer_norm(F.gelu(self.transform(hidden), approximate=True))
        # decode on 2-D rows: the bias add then fuses into the matmul
        # epilogue — on the 3-D form XLA materialises a full-logits layout
        # transpose (measured 7.9 ms / 5.2 GB on the ERNIE config)
        rows = ops.matmul(ops.reshape(h, [-1, hh]), self._decoder_weight,
                          transpose_y=True)
        rows = rows + ops.cast(self.decoder_bias, rows.dtype)
        if masked_positions is not None:
            return rows                                      # (K, vocab)
        return ops.reshape(rows, [b, s, -1])


class BertForPretraining(Layer):
    """MLM + NSP heads (the BERT/ERNIE-base pretraining driver config)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMPredictionHead(
            config, self.bert.embeddings.word_embeddings.weight)
        self.nsp = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        """``masked_positions`` (flat indices into b*s): MLM scores are
        returned for those rows only, (K, vocab) — the pretraining fast
        path; None returns full (b, s, vocab) scores."""
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        return self.cls(seq, masked_positions=masked_positions), \
            self.nsp(pooled)

    def loss(self, input_ids, mlm_labels, nsp_labels, token_type_ids=None,
             attention_mask=None, ignore_index: int = -100):
        """Masked-LM CE (ignoring unmasked positions) + NSP CE."""
        pred, nsp_logits = self(input_ids, token_type_ids, attention_mask)
        labels = mlm_labels._value if isinstance(mlm_labels, Tensor) \
            else jnp.asarray(mlm_labels)
        vocab = pred.shape[-1]
        flat_logits = ops.reshape(pred, [-1, vocab])
        flat_labels = labels.reshape(-1)
        valid = flat_labels != ignore_index
        safe_labels = Tensor(jnp.where(valid, flat_labels, 0).astype(jnp.int32))
        per_tok = F.cross_entropy(flat_logits, safe_labels, reduction="none")
        w = Tensor(valid.astype(jnp.float32))
        mlm_loss = (per_tok * w).sum() / ops.clip((w).sum(), min=1.0)
        nsp_loss = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm_loss + nsp_loss

    def num_params(self) -> int:
        return sum(int(p._value.size) for p in self.parameters())


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))


ErnieForSequenceClassification = BertForSequenceClassification
ErnieForPretraining = BertForPretraining


class BertMLMTransform(Layer):
    """The pre-decode half of the MLM head (transform + LN) as a standalone
    pipeline segment."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = LayerNorm(config.hidden_size)

    def forward(self, hidden):
        return self.layer_norm(
            F.gelu(self.transform(hidden), approximate=True))


class VocabBias(Layer):
    """Per-vocab decoder bias applied after the tied-embedding decode."""

    def __init__(self, vocab_size: int):
        super().__init__()
        from ..nn.parameter import create_parameter
        self.bias = create_parameter([vocab_size], "float32",
                                     default_initializer=I.Constant(0.0))

    def forward(self, logits):
        return logits + ops.cast(self.bias, logits.dtype)


def _tied_mlm_decode(embeddings: BertEmbeddings, hidden):
    """SharedLayerDesc forward_func: decode hidden states against the tied
    word-embedding weight (the reference's shared-weight head,
    ``pp_layers.py:77``). 2-D rows so the downstream bias add fuses into
    the matmul epilogue (see BertLMPredictionHead)."""
    w = embeddings.word_embeddings.weight
    b, s, h = hidden.shape
    rows = ops.matmul(ops.reshape(hidden, [-1, h]), w, transpose_y=True)
    return ops.reshape(rows, [b, s, -1])


def masked_mlm_loss(logits, labels, ignore_index: int = -100):
    """MLM CE over masked positions only (jnp in/out — the PipelineLayer
    loss_fn contract). Matches ``BertForPretraining.loss``'s MLM term."""
    from ..nn.functional.loss import fused_softmax_ce_rows
    vocab = logits.shape[-1]
    flat = logits.reshape(-1, vocab)
    lab = labels.reshape(-1)
    valid = lab != ignore_index
    per_tok = fused_softmax_ce_rows(flat, jnp.where(valid, lab, 0))
    w = valid.astype(jnp.float32)
    return jnp.sum(per_tok * w) / jnp.maximum(jnp.sum(w), 1.0)


def bert_mlm_pipeline(config: BertConfig):
    """BERT/ERNIE MLM pretraining as a generic ``parallel.PipelineLayer``
    — the proof that pipeline parallelism is a framework feature, not a
    per-model one (VERDICT r3 missing #1; ref ``pp_layers.py:162``). The
    desc list mirrors ``BertForPretraining`` minus the NSP head (whose
    pooled[:, 0] input does not flow through the homogeneous block stack;
    the reference's PP GPT configs likewise train the LM objective only):

      [embeddings(shared), layer x N, mlm transform, tied decode(shared),
       vocab bias]

    Use with ``make_sharded_train_step`` on any pp×dp×mp×sharding mesh;
    for pp=1 meshes pass ``loss_fn=model.make_loss_fn()``.
    """
    from ..parallel.pipeline import (LayerDesc, PipelineLayer,
                                     SharedLayerDesc)
    descs = [
        SharedLayerDesc("embed", BertEmbeddings, config),
        *[LayerDesc(BertLayer, config) for _ in range(config.num_layers)],
        LayerDesc(BertMLMTransform, config),
        SharedLayerDesc("embed", BertEmbeddings, config,
                        forward_func=_tied_mlm_decode),
        LayerDesc(VocabBias, config.vocab_size),
    ]
    return PipelineLayer(descs, loss_fn=masked_mlm_loss)


def bert_param_sharding_spec(name: str, shape) -> tuple:
    """TP/ZeRO PartitionSpec per BERT parameter (same Megatron plan as
    :func:`..models.gpt.param_sharding_spec`)."""
    if "qkv_proj.weight" in name or "fc_in.weight" in name:
        return (None, "mp")
    if "out_proj.weight" in name or "fc_out.weight" in name:
        return ("mp", None)
    if "qkv_proj.bias" in name or "fc_in.bias" in name:
        return ("mp",)
    if "word_embeddings.weight" in name:
        return ("mp", None)
    return tuple(None for _ in shape)
