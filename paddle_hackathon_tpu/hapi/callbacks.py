"""Training callbacks (ref ``python/paddle/hapi/callbacks.py``)."""

from __future__ import annotations

import numbers
import os
import time


def _scalar(v):
    """Printable float for a log value, or None to skip it.  Loss values
    from the compiled fit path arrive as DEVICE scalars (the host sync is
    deferred to print time — hapi/compiled.py's async-loss contract);
    0-d arrays fetch here, non-scalars are skipped."""
    if isinstance(v, numbers.Number):
        return float(v)
    if getattr(v, "ndim", None) == 0:
        try:
            return float(v)
        except TypeError:
            return None
    return None


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)

        return call


class ProgBarLogger(Callback):
    """Per-epoch progress printout (ref callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            msgs = [f"step {step}/{self.steps or '?'}"]
            for k, v in (logs or {}).items():
                s = _scalar(v)
                if s is not None:
                    msgs.append(f"{k}: {s:.4f}")
            print(f"Epoch {self.epoch + 1}/{self.epochs} - " + " - ".join(msgs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            msgs = [f"{k}: {s:.4f}" for k, v in (logs or {}).items()
                    if (s := _scalar(v)) is not None]
            print(f"Epoch {epoch + 1}/{self.epochs} done ({dur:.1f}s) - "
                  + " - ".join(msgs))


class ModelCheckpoint(Callback):
    """Periodic save (ref callbacks.py ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class MetricsCallback(Callback):
    """Log/telemetry bridge for the metrics registry
    (``observability.MetricRegistry``) inside ``Model.fit``.

    Every ``log_freq`` train steps it samples the guarded device-health
    gauges and prints a compact line of the registry's key training
    series (step time p50, tokens/sec, compile events, input wait);
    ``on_train_end`` optionally writes the full ``registry.snapshot()``
    JSON to ``snapshot_path`` — the file ``tools/metrics_dump.py``
    pretty-prints and diffs."""

    def __init__(self, log_freq=100, snapshot_path=None, registry=None,
                 verbose=1):
        from ..observability import metrics as _obs
        self.registry = registry or _obs.get_registry()
        self.log_freq = max(int(log_freq), 1)
        self.snapshot_path = snapshot_path
        self.verbose = verbose
        self._begin = None

    def on_train_begin(self, logs=None):
        self._begin = self.registry.snapshot()

    def _line(self):
        reg = self.registry
        parts = []
        fam = reg.get("train_step_seconds")
        if fam is not None:
            for c in fam.children():
                if c.count:
                    parts.append(f"step_p50 {c.quantile(0.5) * 1e3:.1f}ms")
                    break
        tps = reg.total("train_tokens_per_sec")
        if tps:
            parts.append(f"tokens/s {tps:,.0f}")
        builds = reg.total("jit_builds_total")
        if builds:
            parts.append(f"jit_builds {builds:.0f}")
        fam = reg.get("input_wait_seconds")
        if fam is not None:
            for c in fam.children():
                if c.count:
                    parts.append(
                        f"input_wait_p90 {c.quantile(0.9) * 1e3:.1f}ms")
                    break
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if step % self.log_freq:
            return
        from ..observability import metrics as _obs
        _obs.record_device_memory(self.registry)
        if self.verbose:
            line = self._line()
            if line:
                print(f"[metrics] step {step} - {line}")

    def on_train_end(self, logs=None):
        from ..observability import metrics as _obs
        _obs.record_device_memory(self.registry)
        if self.snapshot_path:
            import json
            snap = self.registry.snapshot()
            if self._begin is not None:
                from ..observability.metrics import snapshot_delta
                snap["delta_from_train_begin"] = snapshot_delta(
                    self._begin, snap)["metrics"]
            with open(self.snapshot_path, "w") as f:
                json.dump(snap, f, indent=1)


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (ref EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0

    def _better(self, cur, ref):
        if self.mode == "min":
            return cur < ref - self.min_delta
        return cur > ref + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"early stopping: no {self.monitor} improvement "
                          f"for {self.patience} evals")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler each epoch/step (ref LRScheduler
    callback)."""

    def __init__(self, by_step=False, by_epoch=True):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()
