"""Model summary (ref ``python/paddle/hapi/model_summary.py``)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Print a per-layer parameter table; returns totals dict."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    print(f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}")
    print("-" * (width + 36))
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Estimate forward FLOPs by layer (ref ``python/paddle/hapi/dynamic_flops.py``).

    Runs one forward pass with hooks on leaf layers; counts matmul/conv
    multiply-adds (the MXU work — elementwise ops are ignored, as in the
    reference's per-layer-type count tables).
    """
    from ..nn import layer as _layer_mod

    counts = {}
    handles = []
    custom_ops = custom_ops or {}

    def _count(layer, inp, out):
        cls = type(layer).__name__
        x = inp[0] if isinstance(inp, (tuple, list)) else inp
        o = out[0] if isinstance(out, (tuple, list)) else out
        n = 0
        if cls in custom_ops:
            n = int(custom_ops[cls](layer, inp, out))
        elif hasattr(layer, "weight") and layer.weight is not None:
            w = layer.weight
            if cls.startswith("Conv"):
                # output elements x per-element kernel MACs
                kernel = int(np.prod(w.shape[1:]))
                n = 2 * int(np.prod(o.shape)) * kernel
            elif cls == "Linear":
                n = 2 * int(np.prod(x.shape[:-1])) * int(w.shape[0]) * int(w.shape[1])
            elif cls == "Embedding":
                n = 0
        counts[id(layer)] = counts.get(id(layer), 0) + n

    for sub in net.sublayers(include_self=True):
        if not list(sub.children()):  # leaf layers only
            handles.append(sub.register_forward_post_hook(_count))

    if inputs is None:
        if input_size is None:
            raise ValueError("flops() needs input_size or inputs")
        import paddle_hackathon_tpu as p
        inputs = p.to_tensor(
            np.zeros(input_size, np.float32))
    was_training = getattr(net, "training", False)
    try:
        net.eval()
        net(inputs)
    finally:
        if was_training:
            net.train()
        for h in handles:
            h.remove()
    total = sum(counts.values())
    if print_detail:
        print(f"Total FLOPs: {total:,}")
    return total
