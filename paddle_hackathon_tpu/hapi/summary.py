"""Model summary (ref ``python/paddle/hapi/model_summary.py``)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Print a per-layer parameter table; returns totals dict."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    print(f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}")
    print("-" * (width + 36))
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
