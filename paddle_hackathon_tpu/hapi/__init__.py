"""paddle.hapi equivalent — Keras-like Model.fit (ref ``python/paddle/hapi/``)."""

from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
