"""Keras-like high-level Model API.

Ref ``python/paddle/hapi/model.py`` — ``Model`` (:915), ``fit`` (:1574),
``train_batch`` (:1055), evaluate/predict, save/load. The reference
branches into dygraph vs static adapters; here there is one eager path
(jit-compiling happens inside the layers / fused ops).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..metric import Metric
from .callbacks import CallbackList, ModelCheckpoint, ProgBarLogger


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    # -- configuration ----------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        metrics = _to_list(metrics)
        for m in metrics:
            assert isinstance(m, Metric), (
                f"metrics must be paddle.metric.Metric instances, got {m}")
        self._metrics = metrics

    # -- single-batch ops (ref train_batch:1055) --------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        labels = [_as_tensor(x) for x in _to_list(labels)]
        outputs = self.network(*inputs)
        outs = _to_list(outputs)
        losses = _to_list(self._loss(*(outs + labels)))
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        aux = self._moe_aux_tensor()
        if aux is not None:
            # MoE load-balance aux (same term the compiled path threads
            # into its donated program — the eager tape must train the
            # router too, not just the experts)
            from ..parallel.moe import moe_aux_weight
            total = total + moe_aux_weight(self.network) * aux
        total.backward()
        if aux is not None:
            # report the OPTIMIZED objective as the headline loss so the
            # eager and compiled fit paths log the same quantity — a
            # trace-failure fallback mid-fit must not discontinuously
            # drop the loss series by the aux term
            losses = [total] + losses[1:]
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        out_loss = [float(l.numpy()) for l in losses]
        if aux is not None:
            # observe AFTER the out_loss fetch above already synced the
            # device pipeline — a pre-backward fetch would stall the
            # step on the forward's completion just to feed telemetry
            self._observe_moe_aux(float(aux.numpy()), "hapi_eager")
        return (out_loss, metrics) if metrics else out_loss

    def _moe_aux_tensor(self):
        """Sum of the MoE load-balance aux Tensors the eager forward just
        left on the network's MoELayers, still ON the tape so
        ``backward`` trains the router; None when the network has no
        (traced-this-forward) aux.  Delegates to the single owner of the
        ``l_aux`` walk (``parallel.moe.collect_moe_aux``)."""
        from ..parallel.moe import collect_moe_aux
        return collect_moe_aux(self.network, tensors=True)

    @staticmethod
    def _observe_moe_aux(value, path):
        """train_moe_aux_loss histogram (docs/OBSERVABILITY.md): the
        UNWEIGHTED aux value at the sync points each fit path already
        pays — a rising series means routing is collapsing onto few
        experts faster than the weighted term can rebalance it."""
        from ..observability import metrics as _obs
        _obs.get_registry().histogram(
            "train_moe_aux_loss",
            "MoE load-balance aux loss (unweighted) at loss-fetch sync "
            "points").labels(path=path).observe(float(value))

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        labels = [_as_tensor(x) for x in _to_list(labels)]
        with no_grad():
            outputs = self.network(*inputs)
            outs = _to_list(outputs)
            losses = _to_list(self._loss(*(outs + labels))) if self._loss else []
        metrics = self._update_metrics(outs, labels)
        out_loss = [float(l.numpy()) for l in losses]
        return (out_loss, metrics) if metrics else out_loss

    def _update_metrics(self, outs, labels):
        metrics = []
        for m in self._metrics:
            # Metric protocol (ref hapi/model.py _update_metrics): compute()
            # turns (preds, labels) into the per-batch statistic update()
            # consumes; metrics without compute take raw outputs.
            if hasattr(m, "compute"):
                stat = m.compute(*(outs + labels))
                m.update(*[np.asarray(s_.numpy()) if isinstance(s_, Tensor)
                           else np.asarray(s_) for s_ in _to_list(stat)])
            else:
                m.update(*[t.numpy() for t in outs + labels])
            metrics.append(m.accumulate())
        return metrics

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_as_tensor(x) for x in _to_list(inputs)]
        with no_grad():
            outputs = self.network(*inputs)
        return [o.numpy() for o in _to_list(outputs)]

    # -- loops (ref fit:1574) ---------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, jit_compile=None,
            steps_per_execution=1, prefetch_buffer=2, nan_policy="record",
            checkpoint=None, zero_stage=0, master_weights=False,
            zero_offload=False, grad_overlap=False):
        """Train loop.  ``jit_compile=None`` (default) tries the compiled
        fast path — one donated jitted program per step (see
        ``hapi/compiled.py``) — and falls back to the eager
        ``train_batch`` loop when the network/optimizer isn't
        pure-functional-capable (metrics, grad accumulation, in-place
        buffer updates, Python-side control flow); ``True`` requires it,
        ``False`` forces eager.  ``steps_per_execution=K`` unrolls K
        steps into one ``lax.scan`` program (losses surface per step;
        within a window the learning rate is read once, and a callback
        setting ``stop_training`` mid-window stops AFTER the window's
        remaining updates already ran — stop granularity is K steps).
        ``prefetch_buffer`` batches are staged onto the device ahead of
        compute (``io.device_prefetch``).

        ``nan_policy``: the non-finite-loss watchdog, checked at the
        sync points the loop already pays (``log_freq`` loss fetches,
        epoch end) so it costs no extra device round trip.  A NaN/Inf
        loss always increments ``train_nonfinite_total`` and records a
        flight-recorder event; ``"raise"`` additionally aborts with a
        clear error instead of silently training on garbage (default
        ``"record"``: keep going — some recipes ride through spikes).

        ``checkpoint``: a directory (or
        ``parallel.checkpointing.CheckpointConfig``) enabling async
        crash-safe checkpoints on the compiled path: at the ``log_freq``
        sync points the loop already pays, the train state (params +
        optimizer accumulators + step + data cursor) is snapshot with
        ONE on-device copy dispatch (no added host sync) and committed
        atomically by a background writer; a crashed fit resumes from
        the latest VALID checkpoint — torn shards/manifests are detected
        and fall back — restoring step/epoch/RNG/cursor so the loss
        series continues where it stopped (docs/CHECKPOINTING.md).

        ``zero_stage>=1`` (ZeRO-sharded optimizer, compiled path only):
        the donated K-step program shards every optimizer moment 1/dp
        over the ambient mesh's 'sharding'/'dp' axis
        (``parallel.create_mesh`` first; the batch shards over the same
        axes) — grads reduce-scatter, the update runs on the shard, and
        the updated params all-gather per tensor with the gathers
        overlapping the update tail inside the scanned program.  Cuts
        per-chip optimizer HBM to ~1/dp; the loss series matches the
        replicated update to f32 reassociation (the reduce-scatter
        changes the grad-psum summation order by design).
        ``master_weights=True`` additionally keeps f32 master copies
        sharded alongside the moments (params may then be bf16).
        Checkpoints flow through ``parallel/checkpointing.py``
        unchanged, so resume across a changed dp size re-shards the
        ZeRO state automatically (docs/PARALLELISM.md).

        ``zero_offload=True`` (with ``zero_stage>=1``) parks the
        moments (+ f32 masters) in host RAM and streams the update
        shard-at-a-time through a double-buffered h2d/d2h pipe —
        opt-state HBM goes to ~0 for a stated tokens/s cost
        (docs/PARALLELISM.md "Optimizer offload & overlap").
        ``grad_overlap=True`` schedules each scanned microstep's grad
        reduce-scatter as the grads materialize instead of relying on
        sharding propagation alone — numerics match the fused path to
        f32 reassociation."""
        train_loader = self._to_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = (self._to_loader(eval_data, batch_size, False, False,
                                       num_workers)
                       if eval_data is not None else None)
        cbks = _to_list(callbacks) or [ProgBarLogger(log_freq, verbose)]
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbk = CallbackList(cbks)
        cbk.set_model(self)
        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbk.set_params({"epochs": epochs, "steps": steps, "verbose": verbose})

        if nan_policy not in ("record", "raise"):
            raise ValueError(
                f"nan_policy must be 'record' or 'raise', got {nan_policy!r}")
        trainer = None
        if jit_compile is not False:
            from .compiled import CompiledTrainer, unsupported_reason
            reason = unsupported_reason(self, accumulate_grad_batches)
            if reason is None:
                trainer = CompiledTrainer(self, zero_stage=zero_stage,
                                          master_weights=master_weights,
                                          zero_offload=zero_offload,
                                          grad_overlap=grad_overlap)
            elif jit_compile:
                raise ValueError(
                    f"jit_compile=True, but the compiled fit path is "
                    f"unavailable: {reason}")
            else:
                self._log_fallback_once(
                    f"Model.fit: using the eager path ({reason})")
        if zero_stage and trainer is None:
            # losing the ZeRO sharding must never be silent — the run
            # would quietly hold dp full copies of the optimizer state
            import warnings
            warnings.warn(
                "Model.fit: zero_stage>=1 requires the compiled fit "
                "path; training continues with REPLICATED optimizer "
                "state", RuntimeWarning, stacklevel=2)
        self._fit_used_compiled = trainer is not None

        # crash-safe checkpointing (compiled path only — the eager tape
        # has no functional state to snapshot donation-safely)
        ckpt_driver = None
        start_epoch = 0
        skip_batches = 0
        if checkpoint is not None:
            if trainer is None:
                # direct warn, NOT _log_fallback_once: the once-only
                # flag may already be spent on the eager-fallback log,
                # and losing crash safety must never be silent
                import warnings
                warnings.warn(
                    "Model.fit: checkpoint= requires the compiled fit "
                    "path; training continues WITHOUT crash-safe "
                    "checkpoints", RuntimeWarning, stacklevel=2)
            else:
                from ..parallel.checkpointing import FitCheckpointer
                ckpt_driver = FitCheckpointer(checkpoint)
                ckpt_driver.global_step = int(
                    getattr(self._optimizer, "_step_count", 0) or 0)
                resumed = ckpt_driver.resume(trainer.checkpoint_flat())
                if resumed is not None:
                    placed, start_epoch, skip_batches = resumed
                    trainer.load_checkpoint_flat(placed)

        self.stop_training = False
        logs = {}   # epochs=0: on_train_end still needs a value
        try:
            cbk.on_train_begin()
            for epoch in range(start_epoch, epochs):
                cbk.on_epoch_begin(epoch)
                if ckpt_driver is not None:
                    # capture the shuffle RNG before the epoch's
                    # permutation draws from it (exact-data-order resume)
                    ckpt_driver.mark_epoch()
                for m in self._metrics:
                    m.reset()
                logs = {}
                if trainer is not None:
                    logs, trainer = self._run_compiled_epoch(
                        trainer, train_loader, cbk, log_freq, num_iters,
                        steps_per_execution, prefetch_buffer, nan_policy,
                        epoch=epoch, ckpt=ckpt_driver,
                        skip_batches=(skip_batches
                                      if epoch == start_epoch else 0))
                    self._fit_used_compiled = trainer is not None
                    if ckpt_driver is not None and trainer is not None:
                        # epoch-boundary save: the epoch-end fetch just
                        # drained the pipeline; the snapshot is still
                        # one device-copy dispatch, no extra sync
                        ckpt_driver.maybe_save(
                            trainer.checkpoint_flat(), epoch=epoch + 1,
                            cursor=0, force=True)
                else:
                    from ..observability import tracing as _tr
                    for step, batch in enumerate(train_loader):
                        if num_iters is not None and step >= num_iters:
                            break
                        cbk.on_train_batch_begin(step)
                        ins, lbs = self._split_batch(batch)
                        update = ((step + 1) % accumulate_grad_batches == 0)
                        res = self.train_batch(ins, lbs, update=update)
                        logs = self._pack_logs(res)
                        # eager losses are already host floats
                        # (train_batch float()s them): watch EVERY step —
                        # no log_freq=0 hole, no missed epoch tail
                        self._watch_nonfinite(logs.get("loss"), step,
                                              "hapi_eager", nan_policy)
                        # eager steps are host-synced, so each is a real
                        # liveness signal — without one a wedged eager
                        # fit never trips /healthz?max_age (an absent
                        # beacon passes; only a stale one alerts)
                        _tr.heartbeat("train.hapi_fit")
                        cbk.on_train_batch_end(step, logs)
                        if self.stop_training:
                            break
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              _callbacks=cbk)
                    logs.update({f"eval_{k}": v
                                 for k, v in eval_logs.items()})
                cbk.on_epoch_end(epoch, logs)
                if self.stop_training:
                    break
            cbk.on_train_end(logs)
            if ckpt_driver is not None:
                # drain the writer before returning: a fit that exits
                # with its last checkpoint still queued isn't durable
                ckpt_driver.finish()
            # clean completion: a finished fit must not leave a
            # forever-stale beacon 503ing /healthz?max_age (a crashed
            # fit keeps its beacon — going stale IS the alert)
            from ..observability import tracing as _tr_
            _tr_.remove_beacon("train.hapi_fit")
        except BaseException as e:
            if ckpt_driver is not None:
                # an IN-PROCESS failure can still flush the last parked
                # snapshot — the resume point should be as fresh as the
                # crash allows (a hard kill can't flush; that is what
                # the atomic commit protocol covers)
                try:
                    ckpt_driver.finish()
                except Exception:  # noqa: BLE001 — never mask the crash
                    pass
            # every crashed fit leaves a post-mortem: the flight ring
            # holds the recent step/telemetry events (and the watchdog's
            # nonfinite marks) that led up to the failure
            from ..observability import flight as _flight
            _flight.crash_dump("hapi.Model.fit", e)
            raise
        return logs

    def _log_fallback_once(self, msg):
        if not getattr(self, "_fallback_warned", False):
            self._fallback_warned = True
            import warnings
            warnings.warn(msg, RuntimeWarning, stacklevel=3)

    def _watch_nonfinite(self, value, step, path, nan_policy):
        """Non-finite training watchdog (``fit(nan_policy=...)``): runs
        only at sync points where the loss is already on the host, so it
        never adds a device round trip.  Counts + flight-records every
        NaN/Inf; ``nan_policy='raise'`` aborts with a clear error."""
        import math
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        if math.isfinite(v):
            return
        from ..observability import flight as _flight
        from ..observability import metrics as _obs
        _obs.get_registry().counter(
            "train_nonfinite_total",
            "non-finite (NaN/Inf) losses seen at fit sync points").labels(
                path=path).inc()
        _flight.get_flight_recorder().record(
            "train.nonfinite", path=path, step=int(step), loss=repr(v))
        if nan_policy == "raise":
            raise FloatingPointError(
                f"Model.fit: loss is non-finite ({v}) at step {step} — "
                "aborting instead of training on garbage (check the "
                "learning rate / data; nan_policy='record' continues "
                "and only counts)")

    def _run_compiled_epoch(self, trainer, loader, cbk, log_freq, num_iters,
                            k, prefetch_buffer, nan_policy="record",
                            epoch=0, ckpt=None, skip_batches=0):
        """One epoch through the compiled trainer.  Returns
        ``(logs, trainer_or_None)`` — None when the first program trace
        failed (Python-side control flow in forward, unjittable op) and
        the epoch finished on the eager path instead.

        ``ckpt`` (a ``parallel.checkpointing.FitCheckpointer``) saves at
        the ``log_freq`` fetches below; ``skip_batches`` fast-forwards
        the loader past batches a resumed checkpoint already trained
        (host-side pulls only — no device work for skipped batches)."""
        import itertools
        import time

        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..io.dataloader import device_prefetch
        from ..observability import metrics as _obs
        from ..observability import tracing as _tr

        # step-time/throughput telemetry rides the sync points the loop
        # ALREADY pays (the log_freq loss fetch and the epoch-end
        # block_until_ready) — between them dispatch is async and a wall
        # clock around trainer.run() would measure only Python dispatch.
        _reg = _obs.get_registry()
        _h_step = _reg.histogram(
            "train_step_seconds",
            "mean per-step wall time between loss fetches",
            unit="s").labels(path="hapi_compiled")
        _g_tps = _reg.gauge(
            "train_tokens_per_sec",
            "training throughput between loss fetches "
            "(tokens = batch x seqlen; batch for 1-D samples)").labels(
                path="hapi_compiled")
        # MFU + step-phase attribution (docs/OBSERVABILITY.md, "Trainer
        # MFU and step-phase attribution"): both derive ONLY from
        # timestamps the loop already takes — the program-call wall
        # (dispatch), the log_freq fetch wall (host wait), and the
        # window wall between fetches — so arming them adds no host
        # sync to the step loop.
        _phase_fam = _reg.gauge(
            "train_phase_seconds_per_step",
            "mean wall seconds per step attributed to each step phase "
            "over the last telemetry window (dispatch = Python program "
            "calls, host_wait = loss-fetch stalls, device = the "
            "remainder the async pipeline overlapped)", unit="s")
        _g_phase = {ph: _phase_fam.labels(path="hapi_compiled", phase=ph)
                    for ph in ("dispatch", "host_wait", "device")}
        from ..cost_model import device_peak_flops, train_flops_per_token
        # ONE chip's peak: the hapi compiled trainer is an unsharded
        # jax.jit — it executes on the default device only, so a
        # device_count multiplier would understate MFU by the host's
        # chip count (the sharded auto_parallel.Engine scales by its
        # OWN mesh size instead).  The gauge child is created only when
        # the peak is known — an eager child would export
        # train_mfu=0.0 (alarm-worthy) where the honest answer is
        # "unknown" (docs: unset).
        _peak = device_peak_flops()
        _g_mfu = _reg.gauge(
            "train_mfu",
            "model FLOPs utilization between loss fetches "
            "(analytic cost_model.train_flops_per_token x tokens/s over "
            "device_peak_flops; MoE-active-params-aware; unset when the "
            "chip peak is unknown)").labels(path="hapi_compiled") \
            if _peak else None
        _flops_tok = None      # resolved lazily (needs the seqlen)
        _seqlen = None
        _t_mark = None
        _steps_since = _tokens_since = 0
        _disp_ns = _fetch_ns = 0

        def _telemetry_tick():
            """Close the current telemetry window; returns the phase/
            MFU attribution dict (for the loss_fetch span) or None on
            the first window (compile time must pollute neither the
            step histogram nor the phase split)."""
            nonlocal _t_mark, _steps_since, _tokens_since, _disp_ns, \
                _fetch_ns, _flops_tok
            _tr.heartbeat("train.hapi_fit")   # /healthz last-step recency
            now = time.perf_counter()
            out = None
            if _t_mark is not None and _steps_since:
                dt = now - _t_mark
                if dt > 0:
                    per_step = dt / _steps_since
                    _h_step.observe(per_step)
                    tps = _tokens_since / dt
                    _g_tps.set(tps)
                    disp = _disp_ns / 1e9 / _steps_since
                    wait = _fetch_ns / 1e9 / _steps_since
                    dev = max(per_step - disp - wait, 0.0)
                    _g_phase["dispatch"].set(disp)
                    _g_phase["host_wait"].set(wait)
                    _g_phase["device"].set(dev)
                    out = {"steps": _steps_since,
                           "dispatch_ms_per_step": round(disp * 1e3, 3),
                           "host_wait_ms_per_step": round(wait * 1e3, 3),
                           "device_ms_per_step": round(dev * 1e3, 3)}
                    if _peak:
                        if _flops_tok is None:
                            _flops_tok = train_flops_per_token(
                                self.network, seqlen=_seqlen)
                        mfu = tps * _flops_tok / _peak
                        _g_mfu.set(mfu)
                        out["mfu"] = round(mfu, 4)
            _t_mark, _steps_since, _tokens_since = now, 0, 0
            _disp_ns = _fetch_ns = 0
            return out

        k = max(int(k), 1)
        it = iter(loader)
        pulled = 0
        # resume fast-forward: the checkpoint's cursor counts batches its
        # state already trained this epoch — consume them host-side so
        # the resumed run sees the SAME data order a crash-free run saw
        skip_batches = int(skip_batches)
        for _ in range(skip_batches):
            if next(it, None) is None:
                break
        if num_iters is not None:
            num_iters = max(int(num_iters) - skip_batches, 0)
        consumed = skip_batches   # batches the train STATE has absorbed

        def _leaf(v):
            return v._value if isinstance(v, Tensor) else np.asarray(v)

        def _stack(vals):
            if all(isinstance(v, np.ndarray) for v in vals):
                return np.stack(vals)
            return jnp.stack(vals)

        def host_groups():
            nonlocal pulled
            while not self.stop_training:
                group = []
                while len(group) < k and (num_iters is None
                                          or pulled < num_iters):
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    pulled += 1
                    ins, lbs = self._split_batch(batch)
                    group.append((tuple(_leaf(v) for v in ins),
                                  tuple(_leaf(v) for v in lbs)))
                if not group:
                    return
                xs = tuple(_stack([g[0][i] for g in group])
                           for i in range(len(group[0][0])))
                ys = tuple(_stack([g[1][i] for g in group])
                           for i in range(len(group[0][1])))
                yield (xs, ys)

        step = 0
        last_watched = -1   # last step index the watchdog already saw
        logs = {}
        last = None
        groups = device_prefetch(host_groups(), size=prefetch_buffer)
        for xs, ys in groups:
            # ZeRO program build-or-reuse happens HERE, outside the
            # trainer's hot step path (a structure hit is a dict probe;
            # non-ZeRO trainers return their one program unconditionally)
            trainer.ensure_program(xs, ys)
            t0n = time.perf_counter_ns()
            try:
                losses = trainer.run(xs, ys)
            except Exception as e:  # noqa: BLE001 — unjittable network
                # only TRACE-time failures fall back: an execution-time
                # failure (XlaRuntimeError, e.g. device OOM) happens after
                # the state buffers were donated, so neither the eager
                # replay nor restore_eager could run — surface it
                if trainer.ever_ran or "XlaRuntimeError" in type(e).__name__:
                    raise
                self._log_fallback_once(
                    "Model.fit: compiled trainer failed to trace "
                    f"({type(e).__name__}: {e}); falling back to eager")
                if getattr(trainer, "_zero", None) is not None:
                    # the once-only fallback log above may already be
                    # spent, and losing the ZeRO sharding mid-run must
                    # never be silent: the eager tape trains with dp
                    # FULL replicated copies of the optimizer state
                    import warnings
                    warnings.warn(
                        "Model.fit: the ZeRO-sharded compiled trainer "
                        "fell back to eager MID-RUN; optimizer state is "
                        "REPLICATED for the rest of this fit",
                        RuntimeWarning, stacklevel=2)
                if ckpt is not None:
                    # the once-only fallback log above may already be
                    # spent — losing crash safety mid-run deserves its
                    # own explicit warning, not silence
                    import warnings
                    warnings.warn(
                        "Model.fit: the compiled trainer fell back to "
                        "eager MID-RUN; crash-safe checkpointing is "
                        "DISABLED for the rest of this fit (the eager "
                        "tape has no functional state to snapshot)",
                        RuntimeWarning, stacklevel=2)
                trainer.restore_eager()
                for exs, eys in itertools.chain([(xs, ys)], groups):
                    n = int(jax.tree.leaves(exs)[0].shape[0])
                    for j in range(n):
                        cbk.on_train_batch_begin(step)
                        res = self.train_batch([Tensor(x[j]) for x in exs],
                                               [Tensor(y[j]) for y in eys])
                        logs = self._pack_logs(res)
                        # host floats already — watch every replayed step
                        self._watch_nonfinite(logs.get("loss"), step,
                                              "hapi_eager", nan_policy)
                        _tr.heartbeat("train.hapi_fit")
                        cbk.on_train_batch_end(step, logs)
                        step += 1
                        if self.stop_training:
                            break
                    if self.stop_training:
                        break
                return logs, None
            t1n = time.perf_counter_ns()
            if _tr.tracing_enabled():
                # dispatch wall of the K-step donated program (first call
                # includes trace+compile; the async device time shows up
                # in the loss_fetch spans instead)
                _tr.add_span("hapi.fit.superstep", t0n, t1n, step=step, k=k)
            lead = jax.tree.leaves(xs)[0]   # (K, B, ...) stacked batches
            # tokens = B*S only for token batches (K, B, S); any other
            # rank (vision NCHW etc.) counts samples — shape[2] would be
            # a channel count, not a sequence length
            _seqlen = int(lead.shape[2]) if lead.ndim == 3 else None
            toks_per_step = int(lead.shape[1]) * (_seqlen or 1)
            n = int(losses.shape[0])
            consumed += n
            if ckpt is not None:
                ckpt.advance(n)
            # phase attribution: amortize the K-step program-call wall
            # over its K inner steps — a telemetry window closing MID-
            # superstep (log_freq % k != 0, the default shapes) must
            # get dispatch time proportional to the steps it contains,
            # not a whole superstep's wall dumped into one window
            disp_step_ns = (t1n - t0n) / n
            for j in range(n):
                cbk.on_train_batch_begin(step)
                _steps_since += 1
                _tokens_since += toks_per_step
                _disp_ns += disp_step_ns
                # async loss fetch: the scalar leaves the device only at
                # log_freq boundaries — other steps hand callbacks the
                # device scalar (float()-able on demand)
                v = losses[j]
                if log_freq and step % log_freq == 0:
                    tf0 = time.perf_counter_ns()
                    v = float(v)
                    tf1 = time.perf_counter_ns()
                    _fetch_ns += tf1 - tf0   # phase: host wait on fetch
                    phases = _telemetry_tick()
                    if _tr.tracing_enabled():
                        # host wait for the async device pipeline to
                        # deliver this step's loss scalar — carrying the
                        # closed window's phase/MFU attribution so the
                        # trace answers "where did this window go"
                        _tr.add_span("hapi.fit.loss_fetch", tf0, tf1,
                                     step=step, **(phases or {}))
                    self._watch_nonfinite(v, step, "hapi_compiled",
                                          nan_policy)
                    if trainer.last_aux is not None:
                        # MoE aux ride-along: the loss fetch above
                        # already drained the pipeline, so this is one
                        # more tiny d2h of an already-computed scalar,
                        # not a dispatch
                        self._observe_moe_aux(
                            float(trainer.last_aux[j]), "hapi_compiled")
                    if ckpt is not None:
                        # async checkpoint at the sync point just paid:
                        # one on-device copy dispatch + a queue handoff —
                        # the d2h fetch and disk I/O happen on the
                        # writer thread (parallel/checkpointing.py)
                        ckpt.maybe_save(trainer.checkpoint_flat(),
                                        epoch=epoch, cursor=consumed)
                    last_watched = step
                logs = {"loss": v}
                cbk.on_train_batch_end(step, logs)
                step += 1
                last = (losses, j)
                if self.stop_training:
                    break
            if self.stop_training:
                break
        if last is not None:
            # epoch-end sync; report the loss of the last step callbacks
            # actually saw (a mid-window stop must not report past it)
            losses, j = last
            tf0 = time.perf_counter_ns()
            jax.block_until_ready(losses)
            tf1 = time.perf_counter_ns()
            _fetch_ns += tf1 - tf0
            phases = _telemetry_tick()
            if _tr.tracing_enabled():
                _tr.add_span("hapi.fit.loss_fetch", tf0, tf1,
                             step=step - 1, epoch_end=True,
                             **(phases or {}))
            logs = {"loss": float(losses[j])}
            if step - 1 != last_watched:
                # skip when the final step already hit a log_freq fetch:
                # one bad step must count once, not twice
                self._watch_nonfinite(logs["loss"], step - 1,
                                      "hapi_compiled", nan_policy)
        trainer.sync_optimizer()
        return logs, trainer

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None,
                 _callbacks=None):
        loader = self._to_loader(eval_data, batch_size, False, False,
                                 num_workers)
        cbk = _callbacks or CallbackList(_to_list(callbacks))
        for m in self._metrics:
            m.reset()
        cbk.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbk.on_eval_batch_begin(step)
            ins, lbs = self._split_batch(batch)
            res = self.eval_batch(ins, lbs)
            logs = self._pack_logs(res)
            cbk.on_eval_batch_end(step, logs)
        cbk.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, num_iters=None):
        loader = self._to_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            ins, _ = self._split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- save / load (ref model.py save:1373) -----------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size, dtypes=dtype)

    # -- helpers ----------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        from ..io.dataloader import DataLoader
        from ..io.dataset import Dataset
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # already a loader/iterable

    def _split_batch(self, batch, has_labels=True):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        n_in = len(_to_list(self._inputs))
        if not n_in:
            if has_labels and len(batch) > 1:
                n_in = len(batch) - 1
            else:
                # no inputs spec: cap at the network's forward arity so a
                # labelled dataset still works for predict()
                import inspect
                try:
                    sig = inspect.signature(self.network.forward)
                    n_pos = sum(
                        1 for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD))
                    n_in = min(len(batch), n_pos)
                except (TypeError, ValueError):
                    n_in = len(batch)
        ins = batch[:n_in]
        lbs = batch[n_in:] if has_labels else []
        return ins, lbs

    def _pack_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            for m, v in zip(self._metrics, metrics):
                name = m.name()
                logs[name if isinstance(name, str) else name[0]] = (
                    v if not isinstance(v, (list, tuple)) else v[0])
        else:
            losses = res
        logs["loss"] = losses[0] if isinstance(losses, list) else losses
        return logs
