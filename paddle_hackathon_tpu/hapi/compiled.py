"""Compiled multi-step trainer behind ``Model.fit``.

The eager ``Model.train_batch`` re-dispatches the network op-by-op every
batch, runs the eager tape backward, and forces a device→host sync via
``float(loss)`` — per-step dispatch overhead the hardware never sees in
the hand-rolled jitted train step (``parallel/api.py
make_sharded_train_step``).  This trainer lifts the same design into the
high-level API:

- ONE jitted program per step covering forward + backward + the
  configured optimizer's functional update (``Optimizer.functional_update``),
  with the whole train state (params + accumulators + step counter)
  donated — in-place HBM update, zero copies;
- optional K-step unroll: K prefetched batches stack into a superbatch
  and a single ``lax.scan`` advances K steps per Python→device round trip
  (the step body comes from the shared builder
  ``parallel.api.make_functional_train_step``);
- losses stay device scalars; the fit loop fetches them only at
  ``log_freq`` boundaries and epoch end.

``Model.fit`` falls back transparently to the eager path when the
network/optimizer is not pure-functional-capable — see
``CompiledTrainer.unsupported_reason`` and the trace-failure handling in
``Model._run_compiled_epoch``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as core_random
from ..core.tensor import Tensor
from ..nn.layer import functional_call
from ..observability import metrics as _obs
from ..observability.sanitizers import sanitize_donation
from ..parallel.api import _collect_moe_aux, make_functional_train_step
from ..parallel.moe import moe_aux_weight


def has_moe_layers(network) -> bool:
    """Whether any sublayer carries the MoE aux side channel."""
    return any(hasattr(l, "l_aux")
               for l in network.sublayers(include_self=True))


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _mutating_layer_types():
    """Layer classes whose forward mutates registered buffers in training
    mode (running BN stats, spectral-norm power iterates) — state the
    functional trace cannot carry, so fit must stay eager for them."""
    from ..nn.layers.norm import SpectralNorm, _BatchNormBase
    return (_BatchNormBase, SpectralNorm)


def unsupported_reason(model, accumulate_grad_batches=1):
    """Why ``model`` cannot take the compiled fit path (None = it can).

    Cheap structural checks only; data-dependent Python control flow in
    ``forward`` is caught at first trace and falls back at runtime.
    """
    network, opt, loss = model.network, model._optimizer, model._loss
    if opt is None or loss is None:
        return "prepare() with an optimizer and a loss is required"
    if model._metrics:
        return ("metrics need per-step host outputs; the compiled path "
                "keeps losses on device")
    if accumulate_grad_batches != 1:
        return ("accumulate_grad_batches relies on the eager tape's "
                "update=False staging")
    if not (hasattr(opt, "functional_update")
            and hasattr(opt, "_parameter_list")):
        return (f"{type(opt).__name__} exposes no functional update rule")
    by_id = {id(p) for _, p in network.named_parameters()}
    if any(id(p) not in by_id for p in opt._parameter_list):
        return "optimizer holds parameters outside the fitted network"
    mutating = _mutating_layer_types()
    for layer in network.sublayers(include_self=True):
        if isinstance(layer, mutating):
            return (f"{type(layer).__name__} updates buffers in-place "
                    "during training (running stats)")
    return None


class CompiledTrainer:
    """Functional train state + donated jitted K-step program for one
    ``Model.fit`` run.  Parameters are rebound into the live network
    after every program call (the donated buffers are dead), so eval,
    checkpointing and callbacks keep seeing current weights; optimizer
    accumulators sync back at epoch boundaries via ``sync_optimizer``.

    ``zero_stage>=1`` (``Model.fit(zero_stage=)``) runs the donated
    K-step program ZeRO-sharded over the ambient mesh
    (``parallel.create_mesh``): params replicated, batch sharded over
    the data axes, and every optimizer moment (plus the optional f32
    ``master_weights`` copy) owned 1/dp per rank — the scan body
    reduce-scatters grads, updates the shard, and all-gathers the
    updated params per tensor, so step k+1's gathers overlap the tail
    of step k's update inside the scanned program instead of
    serializing on one fused gather.  The flat checkpoint layout is
    unchanged (the sharded slots ride ``opt::i::slot``), so
    ``parallel.checkpointing.restore_like`` resumes ZeRO state across a
    changed dp size for free.
    """

    def __init__(self, model, seed=0, zero_stage=0, master_weights=False,
                 zero_offload=False, grad_overlap=False,
                 offload_depth=2):
        import warnings

        network, opt, loss = model.network, model._optimizer, model._loss
        self._opt = opt
        self._network = network
        plist = opt._parameter_list
        by_id = {id(p): k for k, p in network.named_parameters()}
        order = [by_id[id(p)] for p in plist]
        self._plist, self._order = plist, order
        self._param_tensors = dict(network.named_parameters())

        self._zero = None
        self._zero_jits = {}
        self._armed_prog = None
        self._n_data = 1
        self._offload = None
        self._offload_depth = int(offload_depth)
        step0 = jnp.asarray(opt._step_count, jnp.int32)
        opt_states = opt.functional_state(plist)
        if int(zero_stage or 0) >= 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.api import get_mesh
            from ..parallel.sharding import ZeroShardInfo, zero_data_axis
            mesh = get_mesh()
            zaxis = zero_data_axis(mesh)
            if zaxis is None:
                warnings.warn(
                    "Model.fit(zero_stage>=1) needs an ambient mesh with "
                    "a >1 'sharding' or 'dp' axis (parallel.create_mesh); "
                    "optimizer state stays replicated for this fit",
                    RuntimeWarning, stacklevel=3)
            else:
                si = ZeroShardInfo(
                    mesh=mesh, axis=zaxis, stage=int(zero_stage),
                    master_weights=bool(master_weights)).with_param_specs(
                        [(None,) * p._value.ndim for p in plist])
                self._zero = si
                self._n_data = int(np.prod([
                    mesh.shape[a] for a in ("dp", "sharding", "ep")
                    if a in mesh.axis_names], dtype=np.int64))
                repl = NamedSharding(mesh, P())
                # params replicated onto the mesh (ZeRO 1/2 keeps the
                # forward's params whole; only the optimizer state
                # shards) — the live network rebinds to the placed
                # arrays so eval/save/checkpoint see mesh arrays
                for t in self._param_tensors.values():
                    t._set_value(jax.device_put(t._value, repl))
                step0 = jax.device_put(step0, repl)
                if zero_offload:
                    # moments (+ masters) live in host RAM; the update
                    # streams shard-at-a-time (parallel.offload) — no
                    # device placement of the optimizer state at all
                    from ..parallel.offload import ZeroOffloadUpdater
                    opt_states = ZeroOffloadUpdater.host_state_for_optimizer(
                        opt, plist, si)
                    self._offload = ZeroOffloadUpdater.for_optimizer(
                        opt, plist, si, depth=self._offload_depth,
                        site="hapi.zero_offload")
                else:
                    from ..parallel.sharding import place_zero_state
                    opt_states = place_zero_state(
                        si, [p._value for p in plist], opt_states)
        if self._zero is None and master_weights:
            warnings.warn(
                "Model.fit(master_weights=True) only takes effect with "
                "zero_stage>=1 on a mesh; ignored", RuntimeWarning,
                stacklevel=3)
        if self._zero is None and zero_offload:
            warnings.warn(
                "Model.fit(zero_offload=True) needs zero_stage>=1 on an "
                "ambient mesh with a >1 data axis; optimizer state stays "
                "device-resident for this fit", RuntimeWarning,
                stacklevel=3)

        params = {k: p._value for k, p in network.named_parameters()}
        _, buffers = network.functional_state()
        self.state = {
            "params": params,
            "opt": opt_states,
            "step": step0,
        }
        from ..parallel.sharding import observe_opt_state_bytes
        if self._offload is not None:
            observe_opt_state_bytes("hapi_compiled", [],
                                    host_tree=opt_states)
        else:
            observe_opt_state_bytes("hapi_compiled", opt_states)
        self.ever_ran = False
        # MoE: thread the load-balance aux INTO the donated program's
        # loss (the PR 2 contract — no extra dispatches) and return it
        # as a ride-along (K,) vector so Model.fit can observe the
        # train_moe_aux_loss metric at the log_freq sync points it
        # already pays for the loss fetch
        self._has_moe = has_moe_layers(network)
        self.last_aux = None
        aux_w = moe_aux_weight(network) if self._has_moe else 0.0

        def forward_loss(p, xs, ys, step):
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            with core_random.rng_scope(rng):
                outs = functional_call(network, p,
                                       tuple(Tensor(x) for x in xs),
                                       buffers=buffers, training=True)
            outs = [Tensor(o) if not isinstance(o, Tensor) else o
                    for o in _to_list(outs)]
            losses = _to_list(loss(*(outs + [Tensor(y) for y in ys])))
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            total = total._value if isinstance(total, Tensor) else total
            total = total.astype(jnp.float32)
            if not self._has_moe:
                return total
            # the forward just traced left each MoELayer's aux on the
            # layer (the _collect_moe_aux side-channel contract the
            # sharded train step already uses)
            aux = _collect_moe_aux(network)
            if aux is None:
                aux = jnp.zeros((), jnp.float32)
            aux = aux.astype(jnp.float32)
            return total + aux_w * aux, aux

        if self._has_moe:
            def grads_of(p, xs, ys, step):
                # has_aux: the aux scalar rides the loss slot as a
                # (total, aux) pair — lax.scan stacks both into (K,)
                # vectors, so the program's outputs grow by K floats,
                # not by a dispatch
                return jax.value_and_grad(
                    lambda pp: forward_loss(pp, xs, ys, step),
                    has_aux=True)(p)
        else:
            def grads_of(p, xs, ys, step):
                return jax.value_and_grad(
                    lambda pp: forward_loss(pp, xs, ys, step))(p)

        if self._offload is not None:
            # grads-only device program: forward + backward + the grad
            # preamble (f32 cast / decay / clip — the exact code the
            # resident ZeRO preamble runs, on the replicated grads), no
            # update.  The update streams through the host pipe in
            # ``run``'s per-step Python loop instead of a lax.scan.
            mw = bool(master_weights)
            has_moe = self._has_moe

            def grads_step(p, step, batch):
                xs, ys = batch
                if has_moe:
                    (total, aux), g = grads_of(p, xs, ys, step)
                else:
                    total, g = grads_of(p, xs, ys, step)
                    aux = jnp.zeros((), jnp.float32)
                vals = [p[k] for k in order]
                gs = opt.preprocess_grads_offload(
                    vals, [g[k] for k in order], master_weights=mw)
                return total, aux, gs, step + 1

            self._grads_step = grads_step
            self._train_step = None
            self._jit = None
            return
        train_step = make_functional_train_step(opt, plist, order, grads_of,
                                                scan_batch=True,
                                                shard_info=self._zero,
                                                grad_overlap=grad_overlap)
        self._train_step = train_step
        # donate the ENTIRE train state: params + accumulators + step all
        # update in place on device; the live network's Tensors rebind to
        # the fresh arrays after each call.  instrument_jit records every
        # trace+compile (a new batch shape = a new program) into
        # jit_builds_total{site=hapi.compiled_trainer}.
        self._jit = sanitize_donation(_obs.instrument_jit(
            jax.jit(train_step, donate_argnums=(0, 1, 2)),
            site="hapi.compiled_trainer"),
            donate_argnums=(0, 1, 2), site="hapi.compiled_trainer")

    def _zero_struct_key(self, xs, ys):
        """(treedef, ranks, ragged?, batch) — the first three select the
        cached program wrapper (``ragged`` = the batch does not divide
        over the data axes, so the replicated-batch flavor applies);
        the batch size rides along for the warning only."""
        leaves, treedef = jax.tree.flatten((xs, ys))
        b = int(np.shape(leaves[0])[1]) if np.ndim(leaves[0]) >= 2 else 0
        return (treedef, tuple(np.ndim(l) for l in leaves),
                bool(b % self._n_data), b)

    def ensure_program(self, xs, ys):
        """Build-or-reuse the ZeRO program for this batch structure.
        ZeRO runs need explicit in/out shardings (batch over the data
        axes, state pinned to its placement so XLA cannot pick a
        re-replicated layout for the donated moments), and the batch
        pytree structure is only known at the first batch — cached per
        (treedef, ranks), mirroring ``make_sharded_train_step``'s
        structure-keyed cache.  The fit loop calls this BEFORE ``run``
        so the hot step path itself never constructs a program
        (PHT002); a structure hit is one dict probe.

        A batch that does not divide over the data axes — typically the
        ragged FINAL batch of an epoch under the default
        ``drop_last=False`` — selects a replicated-batch flavor of the
        program instead of crashing the fit: every rank computes the
        whole (small) batch, which is mathematically the same update
        (the moments stay sharded), it just forgoes dp compute scaling
        for that one superstep.  A once-per-fit warning points at
        ``drop_last=True`` / a divisible batch for runs where EVERY
        batch is indivisible."""
        if self._zero is None:
            return self._jit
        key = self._zero_struct_key(xs, ys)
        if key[2] and not getattr(self, "_warned_ragged", False):
            self._warned_ragged = True
            import warnings
            warnings.warn(
                f"Model.fit(zero_stage>=1): batch size {key[3]} does not "
                f"divide over the mesh's {self._n_data} data-axis "
                "devices; this superstep runs with a REPLICATED batch "
                "(correct, but no dp compute scaling) — pass "
                "drop_last=True or a divisible batch size if this is "
                "not just an epoch's ragged tail", RuntimeWarning,
                stacklevel=3)
        fn = self._zero_jits.get(key[:3])
        if fn is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.api import batch_spec
            leaves, treedef = jax.tree.flatten((xs, ys))
            mesh = self._zero.mesh
            bspec = batch_spec(mesh)
            # ragged (indivisible) batch flavor: batch dim replicated
            bax = (bspec[0] if len(bspec) else None) \
                if not key[2] else None
            repl = NamedSharding(mesh, P())
            param_sh = jax.tree.map(lambda a: a.sharding,
                                    self.state["params"])
            if self._offload is not None:
                # grads-only program over ONE step's batch slice (run's
                # Python loop peels the K dim): nothing donated — params
                # are reused by the streaming update right after
                def leaf_sh1(l):
                    nd = max(np.ndim(l) - 1, 0)
                    spec = ((bax,) + (None,) * (nd - 1))[:nd]
                    return NamedSharding(mesh, P(*spec))

                bsh = jax.tree.unflatten(
                    treedef, [leaf_sh1(l) for l in leaves])
                fn = _obs.instrument_jit(
                    jax.jit(self._grads_step,
                            in_shardings=(param_sh, repl, bsh),
                            out_shardings=repl),
                    site="hapi.compiled_trainer")
                self._zero_jits[key[:3]] = fn
                self._armed_prog = fn
                return fn

            def leaf_sh(l):
                nd = np.ndim(l)
                # stacked (K, B, ...) superbatch leaves: K replicated,
                # batch dim over the data axes, trailing dims whole
                spec = ((None, bax) + (None,) * (nd - 2))[:nd]
                return NamedSharding(mesh, P(*spec))

            bsh = jax.tree.unflatten(treedef, [leaf_sh(l) for l in leaves])
            opt_sh = jax.tree.map(lambda a: a.sharding, self.state["opt"])
            fn = sanitize_donation(_obs.instrument_jit(
                jax.jit(self._train_step, donate_argnums=(0, 1, 2),
                        in_shardings=(param_sh, opt_sh, repl, None, bsh),
                        # repl is a PREFIX spec for the loss slot: it
                        # covers both the (K,) loss vector and the MoE
                        # (losses, aux) pair
                        out_shardings=(param_sh, opt_sh, repl, repl)),
                site="hapi.compiled_trainer"),
                donate_argnums=(0, 1, 2), site="hapi.compiled_trainer")
            self._zero_jits[key[:3]] = fn
        # arm for the next run(): the fit loop calls ensure_program
        # immediately before run with the same batch, so the hot path
        # reads this slot instead of re-deriving the structure key
        self._armed_prog = fn
        return fn

    def run(self, xs, ys):  # pht-lint: hot-root (compiled-trainer step)
        """One compiled superstep over stacked batches (leaves (K, B, …));
        returns the (K,) per-step loss vector as a DEVICE array."""
        if self._zero is None:
            fn = self._jit
        else:
            # armed by the ensure_program the fit loop just called (no
            # re-derivation of the structure key on the hot path); the
            # dict lookup only serves direct callers out of sequence
            fn = self._armed_prog
            if fn is None:
                fn = self._zero_jits.get(self._zero_struct_key(xs, ys)[:3])
            if fn is None:
                # program construction lives OUTSIDE the hot step path —
                # the fit loop (Model._run_compiled_epoch) prepares it
                raise RuntimeError(
                    "CompiledTrainer.run: no program for this batch "
                    "structure — call ensure_program(xs, ys) first")
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        if self._offload is not None:
            return self._run_offload(fn, lr, xs, ys)
        p, s, t, losses = fn(self.state["params"], self.state["opt"],
                             self.state["step"], lr, (xs, ys))
        if self._has_moe:
            # (totals, auxes) — aux stays a device vector until a
            # log_freq fetch reads it alongside the loss
            losses, self.last_aux = losses
        self.state.update(params=p, opt=s, step=t)
        for k, v in p.items():
            self._param_tensors[k]._set_value(v)
        self.ever_ran = True
        return losses

    def _run_offload(self, fn, lr, xs, ys):
        """The offload flavor of one superstep: a Python loop over the K
        stacked batches — each iteration runs the grads-only device
        program, then streams the sharded update through the host pipe
        (``parallel.offload.ZeroOffloadUpdater``).  The host state list
        is REBOUND to fresh arrays every step (never mutated), so a
        checkpoint writer thread holding the previous step's arrays
        stays consistent."""
        k_steps = int(np.shape(jax.tree.leaves(xs)[0])[0])
        params, hstate = self.state["params"], self.state["opt"]
        step = self.state["step"]
        losses, auxes = [], []
        for k in range(k_steps):
            bk = jax.tree.map(lambda a: a[k], (xs, ys))
            total, aux, gs, step = fn(params, step, bk)
            vals = [params[n] for n in self._order]
            new_vals, hstate = self._offload.apply(vals, gs, hstate, lr,
                                                   step)
            params = dict(params)
            params.update(zip(self._order, new_vals))
            losses.append(total)
            auxes.append(aux)
        self.state.update(params=params, opt=hstate, step=step)
        losses = jnp.stack(losses)
        if self._has_moe:
            self.last_aux = jnp.stack(auxes)
        for k, v in params.items():
            self._param_tensors[k]._set_value(v)
        self.ever_ran = True
        return losses

    def checkpoint_flat(self):
        """Flat checkpoint namespace over the CURRENT train state
        (``params::*`` / ``opt::i::slot`` / ``step`` — the layout
        ``parallel.checkpointing`` persists).  Values are the live
        device refs; callers snapshot (``device_snapshot``) before the
        next ``run()`` donates them."""
        from ..parallel.checkpointing import flatten_train_state
        return flatten_train_state(self.state["params"], self.state["opt"],
                                   self.state["step"])

    def load_checkpoint_flat(self, placed):
        """Install a restored flat state (arrays already placed with
        :meth:`checkpoint_flat`'s shardings): train state, the live
        network's Parameters, and the optimizer's accumulators + step
        count all see the resumed values (LR schedules included — one
        tiny host sync of the step scalar, resume-time only)."""
        from ..parallel.checkpointing import unflatten_train_state
        params, opt_states, step = unflatten_train_state(placed)
        self.state = {"params": params, "opt": opt_states, "step": step}
        for k, v in params.items():
            self._param_tensors[k]._set_value(v)
        self.sync_optimizer()

    def sync_optimizer(self):
        """Write accumulators + step count back into the live optimizer
        (one small host sync for the step scalar — epoch-boundary cost)."""
        self._opt.load_functional_state(
            self._plist, self.state["opt"],
            step_count=int(jax.block_until_ready(self.state["step"])))

    def restore_eager(self):
        """Abandon the functional state (trace failure fallback): the live
        network already holds the last good params; accumulators return
        to the optimizer so the eager path continues seamlessly."""
        self.sync_optimizer()
