"""Compiled multi-step trainer behind ``Model.fit``.

The eager ``Model.train_batch`` re-dispatches the network op-by-op every
batch, runs the eager tape backward, and forces a device→host sync via
``float(loss)`` — per-step dispatch overhead the hardware never sees in
the hand-rolled jitted train step (``parallel/api.py
make_sharded_train_step``).  This trainer lifts the same design into the
high-level API:

- ONE jitted program per step covering forward + backward + the
  configured optimizer's functional update (``Optimizer.functional_update``),
  with the whole train state (params + accumulators + step counter)
  donated — in-place HBM update, zero copies;
- optional K-step unroll: K prefetched batches stack into a superbatch
  and a single ``lax.scan`` advances K steps per Python→device round trip
  (the step body comes from the shared builder
  ``parallel.api.make_functional_train_step``);
- losses stay device scalars; the fit loop fetches them only at
  ``log_freq`` boundaries and epoch end.

``Model.fit`` falls back transparently to the eager path when the
network/optimizer is not pure-functional-capable — see
``CompiledTrainer.unsupported_reason`` and the trace-failure handling in
``Model._run_compiled_epoch``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as core_random
from ..core.tensor import Tensor
from ..nn.layer import functional_call
from ..observability import metrics as _obs
from ..observability.sanitizers import sanitize_donation
from ..parallel.api import _collect_moe_aux, make_functional_train_step
from ..parallel.moe import moe_aux_weight


def has_moe_layers(network) -> bool:
    """Whether any sublayer carries the MoE aux side channel."""
    return any(hasattr(l, "l_aux")
               for l in network.sublayers(include_self=True))


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _mutating_layer_types():
    """Layer classes whose forward mutates registered buffers in training
    mode (running BN stats, spectral-norm power iterates) — state the
    functional trace cannot carry, so fit must stay eager for them."""
    from ..nn.layers.norm import SpectralNorm, _BatchNormBase
    return (_BatchNormBase, SpectralNorm)


def unsupported_reason(model, accumulate_grad_batches=1):
    """Why ``model`` cannot take the compiled fit path (None = it can).

    Cheap structural checks only; data-dependent Python control flow in
    ``forward`` is caught at first trace and falls back at runtime.
    """
    network, opt, loss = model.network, model._optimizer, model._loss
    if opt is None or loss is None:
        return "prepare() with an optimizer and a loss is required"
    if model._metrics:
        return ("metrics need per-step host outputs; the compiled path "
                "keeps losses on device")
    if accumulate_grad_batches != 1:
        return ("accumulate_grad_batches relies on the eager tape's "
                "update=False staging")
    if not (hasattr(opt, "functional_update")
            and hasattr(opt, "_parameter_list")):
        return (f"{type(opt).__name__} exposes no functional update rule")
    by_id = {id(p) for _, p in network.named_parameters()}
    if any(id(p) not in by_id for p in opt._parameter_list):
        return "optimizer holds parameters outside the fitted network"
    mutating = _mutating_layer_types()
    for layer in network.sublayers(include_self=True):
        if isinstance(layer, mutating):
            return (f"{type(layer).__name__} updates buffers in-place "
                    "during training (running stats)")
    return None


class CompiledTrainer:
    """Functional train state + donated jitted K-step program for one
    ``Model.fit`` run.  Parameters are rebound into the live network
    after every program call (the donated buffers are dead), so eval,
    checkpointing and callbacks keep seeing current weights; optimizer
    accumulators sync back at epoch boundaries via ``sync_optimizer``.
    """

    def __init__(self, model, seed=0):
        network, opt, loss = model.network, model._optimizer, model._loss
        self._opt = opt
        self._network = network
        plist = opt._parameter_list
        by_id = {id(p): k for k, p in network.named_parameters()}
        order = [by_id[id(p)] for p in plist]
        self._plist, self._order = plist, order
        self._param_tensors = dict(network.named_parameters())
        params = {k: p._value for k, p in network.named_parameters()}
        _, buffers = network.functional_state()
        self.state = {
            "params": params,
            "opt": opt.functional_state(plist),
            "step": jnp.asarray(opt._step_count, jnp.int32),
        }
        self.ever_ran = False
        # MoE: thread the load-balance aux INTO the donated program's
        # loss (the PR 2 contract — no extra dispatches) and return it
        # as a ride-along (K,) vector so Model.fit can observe the
        # train_moe_aux_loss metric at the log_freq sync points it
        # already pays for the loss fetch
        self._has_moe = has_moe_layers(network)
        self.last_aux = None
        aux_w = moe_aux_weight(network) if self._has_moe else 0.0

        def forward_loss(p, xs, ys, step):
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            with core_random.rng_scope(rng):
                outs = functional_call(network, p,
                                       tuple(Tensor(x) for x in xs),
                                       buffers=buffers, training=True)
            outs = [Tensor(o) if not isinstance(o, Tensor) else o
                    for o in _to_list(outs)]
            losses = _to_list(loss(*(outs + [Tensor(y) for y in ys])))
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            total = total._value if isinstance(total, Tensor) else total
            total = total.astype(jnp.float32)
            if not self._has_moe:
                return total
            # the forward just traced left each MoELayer's aux on the
            # layer (the _collect_moe_aux side-channel contract the
            # sharded train step already uses)
            aux = _collect_moe_aux(network)
            if aux is None:
                aux = jnp.zeros((), jnp.float32)
            aux = aux.astype(jnp.float32)
            return total + aux_w * aux, aux

        if self._has_moe:
            def grads_of(p, xs, ys, step):
                # has_aux: the aux scalar rides the loss slot as a
                # (total, aux) pair — lax.scan stacks both into (K,)
                # vectors, so the program's outputs grow by K floats,
                # not by a dispatch
                return jax.value_and_grad(
                    lambda pp: forward_loss(pp, xs, ys, step),
                    has_aux=True)(p)
        else:
            def grads_of(p, xs, ys, step):
                return jax.value_and_grad(
                    lambda pp: forward_loss(pp, xs, ys, step))(p)

        train_step = make_functional_train_step(opt, plist, order, grads_of,
                                                scan_batch=True)
        # donate the ENTIRE train state: params + accumulators + step all
        # update in place on device; the live network's Tensors rebind to
        # the fresh arrays after each call.  instrument_jit records every
        # trace+compile (a new batch shape = a new program) into
        # jit_builds_total{site=hapi.compiled_trainer}.
        self._jit = sanitize_donation(_obs.instrument_jit(
            jax.jit(train_step, donate_argnums=(0, 1, 2)),
            site="hapi.compiled_trainer"),
            donate_argnums=(0, 1, 2), site="hapi.compiled_trainer")

    def run(self, xs, ys):  # pht-lint: hot-root (compiled-trainer step)
        """One compiled superstep over stacked batches (leaves (K, B, …));
        returns the (K,) per-step loss vector as a DEVICE array."""
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        p, s, t, losses = self._jit(self.state["params"], self.state["opt"],
                                    self.state["step"], lr, (xs, ys))
        if self._has_moe:
            # (totals, auxes) — aux stays a device vector until a
            # log_freq fetch reads it alongside the loss
            losses, self.last_aux = losses
        self.state.update(params=p, opt=s, step=t)
        for k, v in p.items():
            self._param_tensors[k]._set_value(v)
        self.ever_ran = True
        return losses

    def checkpoint_flat(self):
        """Flat checkpoint namespace over the CURRENT train state
        (``params::*`` / ``opt::i::slot`` / ``step`` — the layout
        ``parallel.checkpointing`` persists).  Values are the live
        device refs; callers snapshot (``device_snapshot``) before the
        next ``run()`` donates them."""
        from ..parallel.checkpointing import flatten_train_state
        return flatten_train_state(self.state["params"], self.state["opt"],
                                   self.state["step"])

    def load_checkpoint_flat(self, placed):
        """Install a restored flat state (arrays already placed with
        :meth:`checkpoint_flat`'s shardings): train state, the live
        network's Parameters, and the optimizer's accumulators + step
        count all see the resumed values (LR schedules included — one
        tiny host sync of the step scalar, resume-time only)."""
        from ..parallel.checkpointing import unflatten_train_state
        params, opt_states, step = unflatten_train_state(placed)
        self.state = {"params": params, "opt": opt_states, "step": step}
        for k, v in params.items():
            self._param_tensors[k]._set_value(v)
        self.sync_optimizer()

    def sync_optimizer(self):
        """Write accumulators + step count back into the live optimizer
        (one small host sync for the step scalar — epoch-boundary cost)."""
        self._opt.load_functional_state(
            self._plist, self.state["opt"],
            step_count=int(jax.block_until_ready(self.state["step"])))

    def restore_eager(self):
        """Abandon the functional state (trace failure fallback): the live
        network already holds the last good params; accumulators return
        to the optimizer so the eager path continues seamlessly."""
        self.sync_optimizer()
