"""paddle.version (the reference generates this at build time —
``python/setup.py.in`` writes full_version/major/minor/patch/rc and
cuda/cudnn probes; here the accelerator stack is XLA/PJRT)."""

from .. import __version__ as full_version

__all__ = ["full_version", "major", "minor", "patch", "rc", "show",
           "cuda", "cudnn", "istaged", "commit", "mkl", "tpu"]

_parts = full_version.split(".")
major = _parts[0]
minor = _parts[1] if len(_parts) > 1 else "0"
patch = _parts[2] if len(_parts) > 2 else "0"
rc = "0"
istaged = False
commit = "unknown"
with_gpu = "OFF"


def cuda():
    return False


def cudnn():
    return False


def mkl():
    return "OFF"


def tpu():
    """Non-reference probe: is a TPU-class device visible."""
    import jax
    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except Exception:
        return False


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}\nminor: {minor}\npatch: {patch}\nrc: {rc}")
    print(f"commit: {commit}")
