"""Cluster-level (multi-rank) timeline merging.

Reference: ``tools/CrossStackProfiler/`` — ``ProfileFileReader`` /
``NetFileReader`` post-process per-rank profiler dumps into a single
cluster timeline (CspReporter merges per-trainer chrome traces under
distinct pids).

Here each rank's ``profiler.export_chrome_tracing`` JSON becomes one
process row in a merged chrome trace: pid = rank, thread rows preserved,
optional time alignment on a named sync marker (e.g. the per-step
``RecordEvent("step")``) so ranks with skewed host clocks line up.

``stitch_fleet=True`` (CLI ``--stitch-fleet``) adds a serving-fleet
pass: events carrying a fleet trace context (``fleet_rid`` in their
args — the router's ``fleet.*`` spans emit it directly, and the
replicas' ``serving.request`` lifecycle spans carry both ``rid`` and
``fleet_rid``, which maps every other rid-keyed replica span) are
re-homed onto one synthesized "fleet requests" process with one thread
lane per fleet rid — router decision, each placement attempt, and the
replica's per-tick spans read as ONE swimlane per request, across
however many replicas (and, later, processes) served it
(docs/OBSERVABILITY.md, "Fleet telemetry").
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import List, Optional

__all__ = ["merge_traces", "main"]


def _load(path: str) -> dict:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        return json.load(f)


def _rank_of(path: str) -> Optional[int]:
    m = re.search(r"(?:rank|worker|trainer)[_-]?(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def _assign_ranks(ordered: List[str]) -> List[int]:
    """Deterministic pid per trace file.  Named files (rank0/worker1/...)
    keep their encoded rank; unnamed files take the smallest free pids in
    sorted-path order — a mixed named/unnamed merge must NOT silently
    renumber the named ranks (the old behavior: ANY collision between a
    named rank and an unnamed file's positional index threw away every
    name).  Only when the named files themselves collide (two files both
    claiming rank 1) is positional numbering the honest fallback."""
    ranks = [_rank_of(p) for p in ordered]
    named = [r for r in ranks if r is not None]
    if len(set(named)) != len(named):
        return list(range(len(ordered)))
    used = set(named)
    nxt = 0
    for i, r in enumerate(ranks):
        if r is None:
            while nxt in used:
                nxt += 1
            ranks[i] = nxt
            used.add(nxt)
    return ranks


def _flight_rows(path: str, pid: int) -> List[dict]:
    """Flight-recorder dump (``observability/flight.py``) as chrome
    instant events.  The dump's paired ``ts``/``perf_ns`` sample anchors
    its wall-clocked events onto the perf_counter timeline the span/
    counter events live on (valid for dumps from the traced host — the
    perf_counter epoch is per-boot)."""
    dump = _load(path)
    anchor_ns = dump.get("perf_ns")
    if anchor_ns is None:   # pre-anchor dump: cannot place honestly
        import warnings
        warnings.warn(f"flight dump {path} carries no perf_ns anchor; "
                      "skipping (cannot align wall clock to the trace)")
        return []
    wall_off_s = dump.get("ts", 0.0) - anchor_ns / 1e9  # wall = perf + off
    rows = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": f"flight ({os.path.basename(path)})"}},
        {"ph": "M", "name": "process_sort_index", "pid": pid,
         "args": {"sort_index": pid}},
    ]
    for ev in dump.get("events", []):
        args = {k: v for k, v in ev.items() if k not in ("ts", "kind")}
        rows.append({"name": f"flight:{ev.get('kind', '?')}", "ph": "i",
                     "s": "p", "cat": "Flight", "pid": pid, "tid": 0,
                     "ts": (ev.get("ts", 0.0) - wall_off_s) * 1e6,
                     "args": args})
    return rows


def _stitch_fleet(merged: dict) -> dict:
    """Re-home fleet-request events onto per-``fleet_rid`` swimlanes.

    Pass 1 learns ``(pid, rid) -> fleet_rid`` from events whose args
    carry BOTH (the replica lifecycle spans; pid-scoped because engine
    rids are only unique within a process).  Pass 2 moves every event
    that resolves to a fleet rid — directly or via its rid — onto a
    synthesized process (one pid above the ranks) with ``tid =
    fleet_rid``, leaving unrelated events (ticks serving other
    requests, counters, flight rows without a rid) untouched on their
    original rank rows.  Mutates and returns ``merged``."""
    events = merged.get("traceEvents", [])
    rid_map = {}
    for e in events:
        a = e.get("args") or {}
        if a.get("fleet_rid") is not None and a.get("rid") is not None:
            rid_map[(e.get("pid"), a["rid"])] = a["fleet_rid"]
    fleet_pid = max((e["pid"] for e in events
                     if isinstance(e.get("pid"), int)), default=-1) + 1
    lanes = set()
    for e in events:
        if e.get("ph") == "M":
            continue
        a = e.get("args") or {}
        frid = a.get("fleet_rid")
        if frid is None:
            frid = rid_map.get((e.get("pid"), a.get("rid")))
            if frid is None:
                continue
        e["pid"] = fleet_pid
        e["tid"] = frid
        lanes.add(frid)
    if lanes:
        events.append({"ph": "M", "name": "process_name",
                       "pid": fleet_pid,
                       "args": {"name": "fleet requests (rid-stitched)"}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": fleet_pid,
                       "args": {"sort_index": fleet_pid}})
        for frid in sorted(lanes):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": fleet_pid, "tid": frid,
                           "args": {"name": f"fleet_rid={frid}"}})
    return merged


def merge_traces(paths: List[str], align_marker: Optional[str] = None,
                 out_path: Optional[str] = None,
                 flight_paths: Optional[List[str]] = None,
                 stitch_fleet: bool = False) -> dict:
    """Merge per-rank chrome traces into one cluster timeline.

    ``align_marker``: event name whose first occurrence is treated as t=0
    on every rank (clock-skew compensation — the reference aligns on its
    profile step windows). Returns the merged trace dict; writes it to
    ``out_path`` when given.

    ``flight_paths``: flight-recorder dumps to overlay as instant-event
    rows (their own pids above the ranks) — a crash post-mortem lands on
    the same timeline as the spans leading up to it.  Incompatible with
    ``align_marker`` rebasing (the dumps carry no marker), so flight
    rows keep absolute perf-clock time.

    ``stitch_fleet``: run the fleet-request stitching pass (module
    docstring) after the merge — one swimlane per ``fleet_rid``
    spanning router spans and every replica's share of the request.
    """
    if align_marker and flight_paths:
        raise ValueError(
            "align_marker rebases every rank to its marker's t=0, but "
            "flight rows keep absolute perf-clock time (the dumps carry "
            "no marker) — the overlay would land far off the timeline; "
            "pass one or the other")
    merged = {"traceEvents": [], "displayTimeUnit": "ms"}
    ordered = sorted(paths)
    ranks = _assign_ranks(ordered)
    for idx, path in enumerate(ordered):
        rank = ranks[idx]
        trace = _load(path)
        if isinstance(trace, list):   # chrome "JSON Array Format"
            events = trace
        else:
            events = trace.get("traceEvents", [])
        t0 = 0.0
        if align_marker is not None:
            # span events only: a counter series ("ph":"C") that happens
            # to share the marker's name must not skew the alignment
            starts = [e["ts"] for e in events
                      if e.get("name") == align_marker and "ts" in e
                      and e.get("ph") not in ("C", "M")]
            if starts:
                t0 = min(starts)
            else:
                # marker missing on this rank: rebase on its earliest event
                # (keeping absolute time would skew it against the aligned
                # ranks far worse than approximate alignment)
                import warnings
                all_ts = [e["ts"] for e in events
                          if e.get("ph") != "M" and "ts" in e]
                t0 = min(all_ts) if all_ts else 0.0
                warnings.warn(
                    f"align marker {align_marker!r} not found in {path}; "
                    "falling back to the rank's earliest event")
        merged["traceEvents"].append({
            "ph": "M", "name": "process_name", "pid": rank,
            "args": {"name": f"rank {rank} "
                             f"({os.path.basename(path).split('_step')[0]})"},
        })
        merged["traceEvents"].append({
            "ph": "M", "name": "process_sort_index", "pid": rank,
            "args": {"sort_index": rank},
        })
        for e in events:
            if e.get("ph") == "M" and e.get("name") in (
                    "process_name", "process_sort_index"):
                continue  # replaced by the synthesized rank rows above
            e = dict(e)
            e["pid"] = rank
            if e.get("ph") != "M" and "ts" in e:
                e["ts"] = e["ts"] - t0
            merged["traceEvents"].append(e)
    if flight_paths:
        next_pid = (max(ranks) + 1) if ranks else 0
        for j, fp in enumerate(sorted(flight_paths)):
            merged["traceEvents"].extend(_flight_rows(fp, next_pid + j))
    if stitch_fleet:
        _stitch_fleet(merged)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="Merge per-rank profiler chrome traces into one "
                    "cluster timeline (ref tools/CrossStackProfiler)")
    ap.add_argument("trace_dir", help="directory of per-rank *.json traces")
    ap.add_argument("-o", "--out", default="cluster_trace.json")
    ap.add_argument("--align", default=None,
                    help="event name used as per-rank t=0 (clock-skew fix)")
    ap.add_argument("--flight", nargs="*", default=None,
                    help="flight-recorder dump(s) to overlay as instant "
                         "events (incompatible with --align)")
    ap.add_argument("--stitch-fleet", action="store_true",
                    help="re-home fleet-request events (fleet_rid/rid "
                         "args) onto one swimlane per fleet request")
    args = ap.parse_args(argv)
    if args.align and args.flight:
        raise SystemExit("--flight rows keep absolute perf-clock time and "
                         "cannot be rebased by --align; pick one")
    paths = sorted(glob.glob(os.path.join(args.trace_dir, "*.json")) +
                   glob.glob(os.path.join(args.trace_dir, "*.json.gz")))
    if not paths:
        raise SystemExit(f"no traces found under {args.trace_dir}")
    merge_traces(paths, align_marker=args.align, out_path=args.out,
                 flight_paths=args.flight, stitch_fleet=args.stitch_fleet)
    print(f"merged {len(paths)} rank traces -> {args.out}")


if __name__ == "__main__":
    main()
