"""paddle.profiler equivalent.

Ref ``python/paddle/profiler/profiler.py`` — ``Profiler`` (:271) with the
scheduler state machine (``ProfilerState`` :34, ``make_scheduler``),
``export_chrome_tracing`` (:158), ``RecordEvent`` instrumentation
(``platform/profiler/event_tracing.h``) and the statistics report
(``profiler_statistic.py``).

Host events come from a thread-local recorder (the ``HostEventRecorder``
analog, ``host_event_recorder.h``); device activity is captured by
``jax.profiler`` (XLA's tracer plays CUPTI's role) into a TensorBoard
trace directory next to the chrome JSON. Op-level instrumentation hooks
``core.autograd.apply_op`` the way the reference sprinkles RecordEvent
through its op layer.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

from ..core import autograd as _autograd
from ..observability.sanitizers import make_lock

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "make_scheduler",
           "export_chrome_tracing", "export_protobuf", "RecordEvent",
           "load_profiler_result", "SummaryView"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # record and emit trace at this step


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Ref profiler.py make_scheduler — cyclic CLOSED/READY/RECORD windows."""
    period = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


# ---------------------------------------------------------------------------
# Host event recording
# ---------------------------------------------------------------------------

class _HostEvent:
    __slots__ = ("name", "start", "end", "tid", "event_type", "args")

    def __init__(self, name, start, end, tid, event_type="UserDefined",
                 args=None):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid
        self.event_type = event_type
        self.args = args  # chrome-trace args payload (span attrs)


class _Recorder:
    """Process-wide host event sink (ref HostEventRecorder)."""

    def __init__(self):
        self.events = []
        self.counters = []   # (name, labels_tuple, value, t_ns) samples
        # make_lock, not threading.Lock: the lock-order and race
        # sanitizers must see every lock in the process (PHT009 sweep)
        self._lock = make_lock("profiler.recorder")
        self.active = False

    def add(self, ev: _HostEvent):
        if not self.active:
            return
        with self._lock:
            self.events.append(ev)

    def add_counter(self, name, labels, value, t_ns):
        """Metric-update sample (armed into observability.metrics as the
        trace sink while recording) — lands as a chrome "ph":"C" counter
        event next to the spans."""
        if not self.active:
            return
        with self._lock:
            self.counters.append((name, labels, value, t_ns))

    def add_span(self, name, t0_ns, t1_ns, tid, attrs):
        """Finished observability.tracing span (armed as the span sink
        while recording) — a "ph":"X" duration event carrying its attrs
        (request id, slot, step …) as chrome-trace args."""
        if not self.active:
            return
        with self._lock:
            self.events.append(_HostEvent(name, t0_ns, t1_ns, tid, "Span",
                                          args=attrs))

    def drain(self):
        with self._lock:
            evs, self.events = self.events, []
        return evs

    def drain_counters(self):
        with self._lock:
            cs, self.counters = self.counters, []
        return cs


_recorder = _Recorder()


class RecordEvent:
    """Instrumentation scope (ref ``RecordEvent`` event_tracing.h; python
    ``paddle.profiler.RecordEvent``). Usable as context manager or
    begin()/end() pair."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start = None

    def begin(self):
        self._start = time.perf_counter_ns()

    def end(self):
        if self._start is None:
            return
        _recorder.add(_HostEvent(self.name, self._start,
                                 time.perf_counter_ns(),
                                 threading.get_ident(), self.event_type))
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def _op_hook(name: str):
    """Installed into apply_op while a profiler records (the reference
    instruments every op launch)."""
    return RecordEvent(name, event_type="Operator")


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready callback writing chrome://tracing JSON."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_step{prof.step_num}.json")
        events = []
        for ev in prof._events:
            row = {
                "name": ev.name, "ph": "X", "cat": ev.event_type,
                "pid": os.getpid(), "tid": ev.tid,
                "ts": ev.start / 1000.0,       # ns -> us
                "dur": (ev.end - ev.start) / 1000.0,
            }
            if getattr(ev, "args", None):
                row["args"] = ev.args   # span attrs (request id, step, …)
            events.append(row)
        # registry counters/gauges sampled while recording: chrome counter
        # rows ("ph":"C") on the same timeline as the spans.  Label sets
        # render into the event name so each series gets its own row;
        # the value rides args (chrome plots every args key as a series).
        for cname, labels, value, t_ns in getattr(prof, "_counter_events",
                                                  ()):
            if labels:
                cname = cname + "{" + ",".join(
                    f"{k}={v}" for k, v in labels) + "}"
            events.append({
                "name": cname, "ph": "C", "cat": "Metric",
                "pid": os.getpid(), "ts": t_ns / 1000.0,
                "args": {"value": value},
            })
        # compile spans ride a dedicated synthetic lane; name it so the
        # chrome/perfetto row reads "compiles", not a raw tid number
        # (cross_stack.merge_traces preserves tids, so merged traces keep
        # one named compiles lane per rank)
        from ..observability.programs import COMPILES_LANE_TID
        if any(e.get("tid") == COMPILES_LANE_TID for e in events):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": os.getpid(), "tid": COMPILES_LANE_TID,
                           "args": {"name": "compiles"}})
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        prof._last_export = path
        return path

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Protobuf-analog exporter: pickled event list (the reference's
    serialization format is its own proto; the content parity is the event
    stream)."""

    def handler(prof: "Profiler"):
        import pickle
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_step{prof.step_num}.pb")
        with open(path, "wb") as f:
            pickle.dump([(e.name, e.start, e.end, e.tid, e.event_type)
                         for e in prof._events], f)
        prof._last_export = path
        return path

    return handler


def load_profiler_result(path: str):
    import pickle
    with open(path, "rb") as f:
        raw = pickle.load(f)
    return [_HostEvent(*r) for r in raw]


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------

class Profiler:
    """Ref ``Profiler`` profiler.py:271. start/stop/step drive the scheduler
    state machine; on RECORD_AND_RETURN (or stop) the trace is handed to
    on_trace_ready."""

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False,
                 with_flops=False, use_device_tracer: bool = True):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU,
                                                      ProfilerTarget.TPU]
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._events = []
        self._counter_events = []
        self._last_export = None
        self._device_dir = None
        self._device_active = False
        self._use_device_tracer = use_device_tracer
        self._benchmark = _Timer()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._benchmark.begin()
        self.current_state = self._scheduler(self.step_num)
        self._transition(ProfilerState.CLOSED, self.current_state)

    def stop(self):
        self._benchmark.end()
        if not self.timer_only and self.current_state in (
                ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        self._benchmark.step(num_samples)
        prev = self.current_state
        self.step_num += 1
        self.current_state = self._scheduler(self.step_num)
        self._transition(prev, self.current_state)

    def _transition(self, old: ProfilerState, new: ProfilerState):
        if self.timer_only:
            return
        recording_old = old in (ProfilerState.RECORD,
                                ProfilerState.RECORD_AND_RETURN)
        recording_new = new in (ProfilerState.RECORD,
                                ProfilerState.RECORD_AND_RETURN)
        if not recording_old and recording_new:
            self._start_record()
        elif recording_old and old == ProfilerState.RECORD_AND_RETURN:
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            if recording_new:
                self._start_record()
        elif recording_old and not recording_new:
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)

    def _start_record(self):
        _recorder.active = True
        _autograd._profiler_hook = _op_hook
        # mirror registry counter/gauge updates onto the trace timeline
        try:
            from ..observability import metrics as _metrics
            _metrics.set_trace_sink(_recorder.add_counter)
        except Exception:
            pass
        # arm event-level spans (observability.tracing): request/step
        # spans land as "ph":"X" events next to the counter rows.  A
        # user may have enabled tracing independently (flight-recorder
        # spans without a profiler) — remember and restore that.
        try:
            from ..observability import tracing as _tracing
            self._tracing_was_enabled = _tracing.tracing_enabled()
            _tracing.set_span_sink(_recorder.add_span)
            _tracing.enable_tracing()
        except Exception:
            pass
        # also arm the native host tracer (C++ workqueue/dataloader spans)
        try:
            from ..core import native as _native
            if _native.available():
                _native.trace_enable(True)
        except Exception:
            pass
        if self._use_device_tracer and ProfilerTarget.TPU in self.targets:
            try:
                import jax
                self._device_dir = os.path.join(
                    os.environ.get("PADDLE_PROFILER_DIR", "/tmp"),
                    f"xla_trace_{os.getpid()}_{self.step_num}")
                jax.profiler.start_trace(self._device_dir)
                self._device_active = True
            except Exception:
                self._device_active = False

    def _stop_record(self):
        _autograd._profiler_hook = None
        _recorder.active = False
        try:
            from ..observability import metrics as _metrics
            _metrics.set_trace_sink(None)
        except Exception:
            pass
        try:
            from ..observability import tracing as _tracing
            _tracing.set_span_sink(None)
            if not getattr(self, "_tracing_was_enabled", False):
                _tracing.disable_tracing()
        except Exception:
            pass
        self._events = _recorder.drain()
        self._counter_events = _recorder.drain_counters()
        # drain native host-tracer events into the same stream
        try:
            from ..core import native as _native
            if _native.available():
                _native.trace_enable(False)
            if _native.available() and _native.trace_count():
                import tempfile
                with tempfile.NamedTemporaryFile("r", suffix=".json",
                                                 delete=False) as f:
                    path = f.name
                _native.trace_dump_chrome(path)
                _native.trace_clear()
                with open(path) as f:
                    for ev in json.load(f)["traceEvents"]:
                        start = int(ev["ts"] * 1000)
                        self._events.append(_HostEvent(
                            ev["name"], start, start + int(ev["dur"] * 1000),
                            ev["tid"], "Native"))
                os.unlink(path)
        except Exception:
            pass
        if self._device_active:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_active = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- reporting ---------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        """Aggregated per-op table (ref profiler_statistic.py)."""
        agg = {}
        for ev in self._events:
            dur = (ev.end - ev.start) / 1e6  # ms
            a = agg.setdefault(ev.name, [0, 0.0, float("inf"), 0.0])
            a[0] += 1
            a[1] += dur
            a[2] = min(a[2], dur)
            a[3] = max(a[3], dur)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Min':>10}"
                 f"{'Max':>10}{'Avg':>10}"]
        for name, (calls, tot, mn, mx) in rows:
            lines.append(f"{name[:39]:<40}{calls:>8}{tot:>12.3f}{mn:>10.3f}"
                         f"{mx:>10.3f}{tot / calls:>10.3f}")
        report = "\n".join(lines)
        print(report)
        return agg

    @property
    def events(self):
        return list(self._events)

    def benchmark_summary(self):
        return self._benchmark.summary()


class _Timer:
    """Throughput benchmark (ref profiler/timer.py — ips/step stats)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self.step_times = []
        self.samples = []

    def begin(self):
        self._t0 = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._t0 is not None:
            self.step_times.append(now - self._t0)
            self.samples.append(num_samples or 0)
        self._t0 = now

    def end(self):
        self._t0 = None

    def summary(self):
        if not self.step_times:
            return {}
        import numpy as np
        st = np.asarray(self.step_times)
        out = {"steps": len(st), "avg_step_s": float(st.mean()),
               "min_step_s": float(st.min()), "max_step_s": float(st.max())}
        total_samples = sum(self.samples)
        if total_samples:
            out["ips"] = total_samples / float(st.sum())
        return out


class SortedKeys(Enum):
    """Sort key for the stats report (ref profiler/profiler_statistic.py
    SortedKeys)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


__all__.append("SortedKeys")


from . import cross_stack  # noqa: E402,F401
from .cross_stack import merge_traces  # noqa: E402,F401
