"""paddle.distribution equivalent (ref ``python/paddle/distribution/``).

Probability distributions over framework Tensors; sampling uses the
framework RNG stream (``core.random``), densities are taped ops so
log_prob backprops like any other op.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as core_random
from ..core.autograd import apply_op
from ..core.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Exponential", "Gamma", "Laplace",
           "LogNormal", "Gumbel", "Multinomial", "kl_divergence",
           "register_kl"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x, jnp.float32))


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op("dist_prob", lambda lp: jnp.exp(lp),
                        [self.log_prob(value)])

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=tuple(jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=(), seed=0):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        eps = jax.random.normal(key, shp)
        return apply_op("normal_sample",
                        lambda l, s: l + s * eps, [self.loc, self.scale])

    rsample = sample

    def log_prob(self, value):
        def fn(v, l, s):
            var = s * s
            return (-jnp.square(v - l) / (2 * var)
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))
        return apply_op("normal_log_prob", fn,
                        [_t(value), self.loc, self.scale])

    def entropy(self):
        return apply_op(
            "normal_entropy",
            lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            [self.scale])

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return apply_op("normal_var", lambda s: s * s, [self.scale])


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self._base = Normal(loc, scale)
        super().__init__(batch_shape=self._base.batch_shape)

    def sample(self, shape=()):
        return apply_op("lognormal_sample", jnp.exp,
                        [self._base.sample(shape)])

    def log_prob(self, value):
        def fn(v, l, s):
            lv = jnp.log(v)
            var = s * s
            return (-jnp.square(lv - l) / (2 * var) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi) - lv)
        return apply_op("lognormal_log_prob", fn,
                        [_t(value), self._base.loc, self._base.scale])


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(batch_shape=tuple(jnp.broadcast_shapes(
            self.low._value.shape, self.high._value.shape)))

    def sample(self, shape=(), seed=0):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        u = jax.random.uniform(key, shp)
        return apply_op("uniform_sample",
                        lambda lo, hi: lo + (hi - lo) * u,
                        [self.low, self.high])

    rsample = sample

    def log_prob(self, value):
        def fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op("uniform_log_prob", fn,
                        [_t(value), self.low, self.high])

    def entropy(self):
        return apply_op("uniform_entropy",
                        lambda lo, hi: jnp.log(hi - lo),
                        [self.low, self.high])


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("either logits or probs must be given")
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = apply_op("cat_logits", jnp.log, [_t(probs)])
        super().__init__(batch_shape=self.logits._value.shape[:-1])

    @property
    def probs(self):
        return apply_op("cat_probs",
                        lambda l: jax.nn.softmax(l, axis=-1), [self.logits])

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        logits = self.logits._value
        out = jax.random.categorical(key, logits, shape=shp)
        return Tensor(out)

    def log_prob(self, value):
        def fn(l, v):
            lp = jax.nn.log_softmax(l, axis=-1)
            return jnp.take_along_axis(
                lp, v[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return apply_op("cat_log_prob", fn, [self.logits, _t(value)])

    def entropy(self):
        def fn(l):
            lp = jax.nn.log_softmax(l, axis=-1)
            return -jnp.sum(jnp.exp(lp) * lp, axis=-1)
        return apply_op("cat_entropy", fn, [self.logits])


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs = _t(probs)
        super().__init__(batch_shape=self.probs._value.shape)

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            key, self.probs._value, shp).astype(jnp.float32))

    def log_prob(self, value):
        def fn(p, v):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return apply_op("bern_log_prob", fn, [self.probs, _t(value)])

    def entropy(self):
        def fn(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return apply_op("bern_entropy", fn, [self.probs])


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(batch_shape=tuple(jnp.broadcast_shapes(
            self.alpha._value.shape, self.beta._value.shape)))

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        return Tensor(jax.random.beta(key, self.alpha._value,
                                      self.beta._value, shp))

    def log_prob(self, value):
        def fn(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.betaln(a, b)))
        return apply_op("beta_log_prob", fn,
                        [_t(value), self.alpha, self.beta])

    def entropy(self):
        def fn(a, b):
            dg = jax.scipy.special.digamma
            return (jax.scipy.special.betaln(a, b)
                    - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))
        return apply_op("beta_entropy", fn, [self.alpha, self.beta])


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _t(concentration)
        shp = self.concentration._value.shape
        super().__init__(batch_shape=shp[:-1], event_shape=shp[-1:])

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(
            key, self.concentration._value, shp or None))

    def log_prob(self, value):
        def fn(v, c):
            return (jnp.sum((c - 1) * jnp.log(v), axis=-1)
                    + jax.scipy.special.gammaln(jnp.sum(c, axis=-1))
                    - jnp.sum(jax.scipy.special.gammaln(c), axis=-1))
        return apply_op("dirichlet_log_prob", fn,
                        [_t(value), self.concentration])


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _t(rate)
        super().__init__(batch_shape=self.rate._value.shape)

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        e = jax.random.exponential(key, shp)
        return apply_op("exp_sample", lambda r: e / r, [self.rate])

    def log_prob(self, value):
        return apply_op("exp_log_prob",
                        lambda v, r: jnp.log(r) - r * v,
                        [_t(value), self.rate])


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(batch_shape=tuple(jnp.broadcast_shapes(
            self.concentration._value.shape, self.rate._value.shape)))

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        g = jax.random.gamma(key, self.concentration._value, shp)
        return apply_op("gamma_sample", lambda r: g / r, [self.rate])

    def log_prob(self, value):
        def fn(v, c, r):
            return (c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(c))
        return apply_op("gamma_log_prob", fn,
                        [_t(value), self.concentration, self.rate])


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=tuple(jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        e = jax.random.laplace(key, shp)
        return apply_op("laplace_sample",
                        lambda l, s: l + s * e, [self.loc, self.scale])

    def log_prob(self, value):
        return apply_op(
            "laplace_log_prob",
            lambda v, l, s: -jnp.abs(v - l) / s - jnp.log(2 * s),
            [_t(value), self.loc, self.scale])


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(batch_shape=tuple(jnp.broadcast_shapes(
            self.loc._value.shape, self.scale._value.shape)))

    def sample(self, shape=()):
        key = core_random.split_key()
        shp = tuple(shape) + self.batch_shape
        g = jax.random.gumbel(key, shp)
        return apply_op("gumbel_sample",
                        lambda l, s: l + s * g, [self.loc, self.scale])

    def log_prob(self, value):
        def fn(v, l, s):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return apply_op("gumbel_log_prob", fn,
                        [_t(value), self.loc, self.scale])


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shp = self.probs._value.shape
        super().__init__(batch_shape=shp[:-1], event_shape=shp[-1:])

    def sample(self, shape=()):
        key = core_random.split_key()
        n = self.probs._value.shape[-1]
        logits = jnp.log(jnp.clip(self.probs._value, 1e-12))
        draws = jax.random.categorical(
            key, logits, shape=tuple(shape) + self.batch_shape
            + (self.total_count,))
        counts = jax.nn.one_hot(draws, n).sum(axis=-2)
        return Tensor(counts)

    def log_prob(self, value):
        def fn(v, p):
            logp = jnp.log(jnp.clip(p, 1e-12))
            gl = jax.scipy.special.gammaln
            return (gl(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(gl(v + 1.0), axis=-1)
                    + jnp.sum(v * logp, axis=-1))
        return apply_op("multinomial_log_prob", fn, [_t(value), self.probs])


# ---------------------------------------------------------------------------
# KL divergence registry (ref distribution/kl.py register_kl)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def fn(pl, ps, ql, qs):
        vr = jnp.square(ps / qs)
        return 0.5 * (vr + jnp.square(ql - pl) / jnp.square(qs)
                      - 1.0 - jnp.log(vr))
    return apply_op("kl_normal", fn, [p.loc, p.scale, q.loc, q.scale])


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def fn(pl, ph, ql, qh):
        inside = (ql <= pl) & (ph <= qh)
        return jnp.where(inside, jnp.log((qh - ql) / (ph - pl)), jnp.inf)
    return apply_op("kl_uniform", fn, [p.low, p.high, q.low, q.high])


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    def fn(pl, ql):
        plog = jax.nn.log_softmax(pl, axis=-1)
        qlog = jax.nn.log_softmax(ql, axis=-1)
        return jnp.sum(jnp.exp(plog) * (plog - qlog), axis=-1)
    return apply_op("kl_categorical", fn, [p.logits, q.logits])


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    def fn(pp, qp):
        pp = jnp.clip(pp, 1e-7, 1 - 1e-7)
        qp = jnp.clip(qp, 1e-7, 1 - 1e-7)
        return (pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))
    return apply_op("kl_bernoulli", fn, [p.probs, q.probs])


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def fn(pa, pb, qa, qb):
        dg = jax.scipy.special.digamma
        bl = jax.scipy.special.betaln
        return (bl(qa, qb) - bl(pa, pb)
                + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
                + (qa - pa + qb - pb) * dg(pa + pb))
    return apply_op("kl_beta", fn, [p.alpha, p.beta, q.alpha, q.beta])


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (ref
    distribution/exponential_family.py): entropy via the Bregman identity
    over the log-normalizer (autodiff replaces the reference's manual
    gradient of _log_normalizer)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        import jax as _jax
        nats = [n._value if isinstance(n, Tensor) else jnp.asarray(n)
                for n in self._natural_parameters]

        def lognorm(*ns):
            out = self._log_normalizer(*[Tensor(n) for n in ns])
            return (out._value if isinstance(out, Tensor) else out).sum()

        val = self._log_normalizer(*[Tensor(n) for n in nats])
        val = val._value if isinstance(val, Tensor) else val
        grads = _jax.grad(lognorm, argnums=tuple(range(len(nats))))(*nats)
        ent = val - self._mean_carrier_measure
        for n, g in zip(nats, grads):
            ent = ent - n * g
        return Tensor(ent)


class Independent(Distribution):
    """Reinterprets batch dims of a base distribution as event dims
    (ref distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        self._base = base
        self._rank = int(reinterpreted_batch_rank)
        shape = tuple(base.batch_shape)
        cut = len(shape) - self._rank
        super().__init__(shape[:cut], shape[cut:] + tuple(base.event_shape))

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        lp = self._base.log_prob(value)
        axes = tuple(range(-self._rank, 0))
        return apply_op("independent_log_prob",
                        lambda v: jnp.sum(v, axis=axes), [_t(lp)])

    def entropy(self):
        ent = self._base.entropy()
        axes = tuple(range(-self._rank, 0))
        return apply_op("independent_entropy",
                        lambda v: jnp.sum(v, axis=axes), [_t(ent)])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance


class TransformedDistribution(Distribution):
    """Pushforward of a base distribution through transforms
    (ref distribution/transformed_distribution.py)."""

    def __init__(self, base, transforms):
        from .transform import Transform, ChainTransform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("transforms must be Transform instances")
        self._base = base
        self._transforms = list(transforms)
        chain = ChainTransform(self._transforms) if self._transforms else None
        base_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        if chain and len(base_shape) < chain._domain.event_rank:
            raise ValueError(
                f"base distribution rank {len(base_shape)} is smaller than "
                f"the chain's domain event rank {chain._domain.event_rank}")
        shape = chain.forward_shape(base_shape) if chain else base_shape
        # ref transformed_distribution.py:76-77: the transformed event rank
        # is the chain codomain's plus whatever base event dims the chain's
        # domain does not consume
        if chain:
            event_rank = chain._codomain.event_rank + max(
                len(base.event_shape) - chain._domain.event_rank, 0)
        else:
            event_rank = len(base.event_shape)
        super().__init__(shape[:len(shape) - event_rank],
                         shape[len(shape) - event_rank:])

    def sample(self, shape=()):
        x = self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self._base.rsample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        from .transform import _sum_rightmost

        lp = None
        y = _t(value)
        event_rank = len(self.event_shape)
        for t in reversed(self._transforms):
            x = t.inverse(y)
            event_rank += t._domain.event_rank - t._codomain.event_rank
            ldj = _sum_rightmost(t.forward_log_det_jacobian(x),
                                 event_rank - t._domain.event_rank)
            lp = ldj if lp is None else lp + ldj
            y = x
        base_lp = _sum_rightmost(self._base.log_prob(y),
                                 event_rank - len(self._base.event_shape))
        return base_lp - lp if lp is not None else base_lp


from . import constraint, variable  # noqa: E402
from .transform import (  # noqa: E402
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform)

Lognormal = LogNormal  # alias matching newer upstream releases (the
# reference snapshot only has LogNormal); kept for forward compatibility

__all__ += ["ExponentialFamily", "Independent", "TransformedDistribution",
            "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
            "ExpTransform", "IndependentTransform", "PowerTransform",
            "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
            "StackTransform", "StickBreakingTransform", "TanhTransform",
            "Lognormal", "constraint", "variable"]
