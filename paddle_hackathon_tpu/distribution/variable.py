"""Random-variable descriptors: discreteness, event rank and constraint
(ref ``python/paddle/distribution/variable.py:18-104``)."""

from __future__ import annotations

from . import constraint as _constraint


class Variable:
    """Random variable of a probability distribution
    (ref ``variable.py:18``)."""

    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self._is_discrete = is_discrete
        self._event_rank = event_rank
        self._constraint = constraint

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, value):
        """Check whether the 'value' meets the constraint conditions."""
        return self._constraint(value)


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, _constraint.real)


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, _constraint.positive)


class Independent(Variable):
    """Reinterprets some of the rightmost batch axes as event axes
    (ref ``variable.py:57``)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(
            base.is_discrete,
            base.event_rank + reinterpreted_batch_rank)

    def constraint(self, value):
        ret = self._base.constraint(value)
        if ret.ndim < self._reinterpreted_batch_rank:
            raise ValueError(
                "Input dimensions must be equal or greater than "
                f"{self._reinterpreted_batch_rank}")
        import jax.numpy as jnp
        from ..core.autograd import apply_op
        axes = tuple(range(-self._reinterpreted_batch_rank, 0))
        return apply_op("independent_constraint",
                        lambda v: jnp.all(v, axis=axes), [ret])


class Stack(Variable):
    def __init__(self, vars, axis=0):  # noqa: A002
        self._vars = vars
        self._axis = axis

    @property
    def is_discrete(self):
        return any(var.is_discrete for var in self._vars)

    @property
    def event_rank(self):
        # ref variable.py:95-99: the stacking axis only adds an event rank
        # when it falls left of every component's event block
        rank = max(var.event_rank for var in self._vars)
        if self._axis + rank < 0:
            rank += 1
        return rank

    def constraint(self, value):
        import jax.numpy as jnp
        from ..core.autograd import apply_op
        from ..core.tensor import Tensor

        def fn(v):
            cols = []
            for i, var in enumerate(self._vars):
                out = var.constraint(Tensor(jnp.take(v, i, axis=self._axis)))
                cols.append(out._value if isinstance(out, Tensor) else out)
            return jnp.stack(cols, axis=self._axis)

        value = value if isinstance(value, Tensor) else Tensor(
            jnp.asarray(value))
        return apply_op("stack_constraint", fn, [value])


real = Real()
positive = Positive()
