"""Random-variable transformations (ref
``python/paddle/distribution/transform.py:35-1266``).

Each ``Transform`` maps a random variable through a function with a
tractable log-det-Jacobian, the building block of
``TransformedDistribution``.  The full reference family is implemented:
Abs, Affine, Chain, Exp, Independent, Power, Reshape, Sigmoid, Softmax,
Stack, StickBreaking, Tanh.  Math runs on jax through the framework's
taped ``apply_op`` so transforms are differentiable in eager mode.
"""

from __future__ import annotations

import enum
import functools
import math
import operator

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from . import constraint, variable

__all__ = [
    'Transform', 'AbsTransform', 'AffineTransform', 'ChainTransform',
    'ExpTransform', 'IndependentTransform', 'PowerTransform',
    'ReshapeTransform', 'SigmoidTransform', 'SoftmaxTransform',
    'StackTransform', 'StickBreakingTransform', 'TanhTransform',
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _op(name, fn, *tensors):
    return apply_op(name, fn, [_t(x) for x in tensors])


def _sum_rightmost(value, n):
    """Sum the rightmost ``n`` axes (shared by ChainTransform and
    TransformedDistribution.log_prob)."""
    if n <= 0:
        return _t(value)
    return _op("sum_rightmost",
               lambda v: jnp.sum(v, axis=tuple(range(-n, 0))), value)


class Type(enum.Enum):
    """Mapping type of a transformation (ref ``transform.py:35``)."""
    BIJECTION = 'bijection'      # bijective (injective and surjective)
    INJECTION = 'injection'      # injective only
    SURJECTION = 'surjection'    # surjective only
    OTHER = 'other'              # general

    @classmethod
    def is_injective(cls, _type):
        return _type in (cls.BIJECTION, cls.INJECTION)


class Transform:
    r"""Base class for transformations of random variables
    (ref ``transform.py:50``).

    Subclasses implement ``_forward``/``_inverse`` and one of
    ``_forward_log_det_jacobian`` / ``_inverse_log_det_jacobian``; the
    public methods derive the other direction.
    """

    _type = Type.INJECTION

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, input):  # noqa: A002
        """Apply as a function: a Distribution input builds a
        TransformedDistribution, a Transform composes a chain."""
        from . import Distribution, TransformedDistribution
        if isinstance(input, Distribution):
            return TransformedDistribution(input, [self])
        if isinstance(input, Transform):
            return ChainTransform([self, input])
        return self.forward(_t(input))

    def forward(self, x):
        """y = f(x)."""
        return self._forward(_t(x))

    def inverse(self, y):
        """x = f^{-1}(y)."""
        return self._inverse(_t(y))

    def forward_log_det_jacobian(self, x):
        """log|det J_f(x)|."""
        if not self._is_injective():
            raise NotImplementedError(
                "forward_log_det_jacobian is only defined for injective "
                "transforms")
        x = _t(x)
        if hasattr(type(self), '_forward_log_det_jacobian') and \
                type(self)._forward_log_det_jacobian is not \
                Transform._forward_log_det_jacobian:
            return self._forward_log_det_jacobian(x)
        return -self._inverse_log_det_jacobian(self.forward(x))

    def inverse_log_det_jacobian(self, y):
        """log|det J_{f^{-1}}(y)| = -log|det J_f(f^{-1}(y))|."""
        y = _t(y)
        if hasattr(type(self), '_inverse_log_det_jacobian') and \
                type(self)._inverse_log_det_jacobian is not \
                Transform._inverse_log_det_jacobian:
            return self._inverse_log_det_jacobian(y)
        return -self.forward_log_det_jacobian(self.inverse(y))

    def forward_shape(self, shape):
        """Shape of forward(x) given shape of x."""
        return self._forward_shape(tuple(shape))

    def inverse_shape(self, shape):
        return self._inverse_shape(tuple(shape))

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.real

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            f'{type(self).__name__} implements neither '
            '_forward_log_det_jacobian nor _inverse_log_det_jacobian')

    def _inverse_log_det_jacobian(self, y):
        raise NotImplementedError(
            f'{type(self).__name__} implements neither '
            '_forward_log_det_jacobian nor _inverse_log_det_jacobian')

    def _forward_shape(self, shape):
        return shape

    def _inverse_shape(self, shape):
        return shape


class AbsTransform(Transform):
    r"""y = |x| — surjective onto [0, inf); ``inverse`` returns the set
    inverse ``(-y, y)`` (ref ``transform.py:318``)."""

    _type = Type.SURJECTION

    def _forward(self, x):
        return _op("abs_fwd", jnp.abs, x)

    def _inverse(self, y):
        return _op("abs_inv_neg", operator.neg, y), _t(y)

    def _inverse_log_det_jacobian(self, y):
        zero = _op("abs_ildj", lambda v: jnp.zeros((1,), v.dtype), y)
        return zero, zero

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.positive


class AffineTransform(Transform):
    r"""y = loc + scale * x (ref ``transform.py:390``)."""

    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self._loc = _t(loc)
        self._scale = _t(scale)
        super().__init__()

    @property
    def loc(self):
        return self._loc

    @property
    def scale(self):
        return self._scale

    def _forward(self, x):
        return _op("affine_fwd", lambda v, l, s: l + s * v,
                   x, self._loc, self._scale)

    def _inverse(self, y):
        return _op("affine_inv", lambda v, l, s: (v - l) / s,
                   y, self._loc, self._scale)

    def _forward_log_det_jacobian(self, x):
        return _op("affine_fldj",
                   lambda v, s: jnp.broadcast_to(jnp.log(jnp.abs(s)),
                                                 jnp.broadcast_shapes(
                                                     v.shape, s.shape)),
                   x, self._scale)

    def _broadcast(self, shape):
        return tuple(jnp.broadcast_shapes(
            tuple(shape), tuple(self._loc.shape), tuple(self._scale.shape)))

    def _forward_shape(self, shape):
        return self._broadcast(shape)

    def _inverse_shape(self, shape):
        return self._broadcast(shape)

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.real


class ChainTransform(Transform):
    r"""Composition of transforms, applied left-to-right
    (ref ``transform.py:467``)."""

    def __init__(self, transforms):
        if not isinstance(transforms, (list, tuple)) or not all(
                isinstance(t, Transform) for t in transforms):
            raise TypeError(
                "transforms must be a list/tuple of Transform instances")
        self.transforms = tuple(transforms)
        super().__init__()

    def _is_injective(self):
        return all(t._is_injective() for t in self.transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        value = 0.0
        event_rank = self._domain.event_rank
        for t in self.transforms:
            value = value + _sum_rightmost(
                t.forward_log_det_jacobian(x),
                event_rank - t._domain.event_rank)
            x = t.forward(x)
            event_rank += t._codomain.event_rank - t._domain.event_rank
        return value



    def _forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def _inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape

    @property
    def _domain(self):
        # DP over the chain for the input-rank lower bound
        # (ref transform.py:549-576): N(i) = max(N(i+1) - delta(ti), ti(in))
        domain = self.transforms[0]._domain
        event_rank = self.transforms[-1]._codomain.event_rank
        for t in reversed(self.transforms):
            event_rank -= t._codomain.event_rank - t._domain.event_rank
            event_rank = max(event_rank, t._domain.event_rank)
        if event_rank == domain.event_rank:
            return domain
        return variable.Independent(domain, event_rank - domain.event_rank)

    @property
    def _codomain(self):
        # ref transform.py:578-587
        codomain = self.transforms[-1]._codomain
        event_rank = self.transforms[0]._domain.event_rank
        for t in self.transforms:
            event_rank += t._codomain.event_rank - t._domain.event_rank
            event_rank = max(event_rank, t._codomain.event_rank)
        if event_rank == codomain.event_rank:
            return codomain
        return variable.Independent(codomain,
                                    event_rank - codomain.event_rank)


class ExpTransform(Transform):
    r"""y = exp(x) (ref ``transform.py:590``)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        return _op("exp_fwd", jnp.exp, x)

    def _inverse(self, y):
        return _op("exp_inv", jnp.log, y)

    def _forward_log_det_jacobian(self, x):
        return _t(x)

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.positive


class IndependentTransform(Transform):
    r"""Wraps a base transform, reinterpreting the ``reinterpreted_batch_rank``
    rightmost batch axes as event axes: the log-det-Jacobian sums over them
    (ref ``transform.py:639``)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Transform):
            raise TypeError(
                f"Expected 'base' is Transform type, but got {type(base)}")
        if reinterpreted_batch_rank <= 0:
            raise ValueError(
                "Expected 'reinterpreted_batch_rank' greater than zero, "
                f"but got {reinterpreted_batch_rank}")
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__()

    def _is_injective(self):
        return self._base._is_injective()

    def _forward(self, x):
        x = _t(x)
        if x.ndim < self._domain.event_rank:
            raise ValueError("input rank is less than the event rank")
        return self._base.forward(x)

    def _inverse(self, y):
        y = _t(y)
        if y.ndim < self._codomain.event_rank:
            raise ValueError("input rank is less than the event rank")
        return self._base.inverse(y)

    def _forward_log_det_jacobian(self, x):
        return _sum_rightmost(self._base.forward_log_det_jacobian(x),
                              self._reinterpreted_batch_rank)

    def _forward_shape(self, shape):
        return self._base.forward_shape(shape)

    def _inverse_shape(self, shape):
        return self._base.inverse_shape(shape)

    @property
    def _domain(self):
        return variable.Independent(self._base._domain,
                                    self._reinterpreted_batch_rank)

    @property
    def _codomain(self):
        return variable.Independent(self._base._codomain,
                                    self._reinterpreted_batch_rank)


class PowerTransform(Transform):
    r"""y = x^power (ref ``transform.py:730``)."""

    _type = Type.BIJECTION

    def __init__(self, power):
        self._power = _t(power)
        super().__init__()

    @property
    def power(self):
        return self._power

    def _forward(self, x):
        return _op("power_fwd", lambda v, p: jnp.power(v, p), x, self._power)

    def _inverse(self, y):
        return _op("power_inv", lambda v, p: jnp.power(v, 1.0 / p),
                   y, self._power)

    def _forward_log_det_jacobian(self, x):
        return _op("power_fldj",
                   lambda v, p: jnp.log(jnp.abs(p * jnp.power(v, p - 1))),
                   x, self._power)

    def _forward_shape(self, shape):
        return tuple(jnp.broadcast_shapes(tuple(shape),
                                          tuple(self._power.shape)))

    def _inverse_shape(self, shape):
        return tuple(jnp.broadcast_shapes(tuple(shape),
                                          tuple(self._power.shape)))

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.positive


class ReshapeTransform(Transform):
    r"""Reshapes the event shape (ref ``transform.py:793``)."""

    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        if not isinstance(in_event_shape, (list, tuple)) or \
                not isinstance(out_event_shape, (list, tuple)):
            raise TypeError("event shapes must be list or tuple")
        if functools.reduce(operator.mul, in_event_shape, 1) != \
                functools.reduce(operator.mul, out_event_shape, 1):
            raise ValueError(
                f"in_event_shape {in_event_shape} and out_event_shape "
                f"{out_event_shape} have different numbers of elements")
        self._in_event_shape = tuple(in_event_shape)
        self._out_event_shape = tuple(out_event_shape)
        super().__init__()

    @property
    def in_event_shape(self):
        return self._in_event_shape

    @property
    def out_event_shape(self):
        return self._out_event_shape

    def _forward(self, x):
        out_shape = tuple(_t(x).shape[:_t(x).ndim - len(
            self._in_event_shape)]) + self._out_event_shape
        return _op("reshape_fwd", lambda v: jnp.reshape(v, out_shape), x)

    def _inverse(self, y):
        in_shape = tuple(_t(y).shape[:_t(y).ndim - len(
            self._out_event_shape)]) + self._in_event_shape
        return _op("reshape_inv", lambda v: jnp.reshape(v, in_shape), y)

    def _forward_log_det_jacobian(self, x):
        batch = tuple(_t(x).shape[:_t(x).ndim - len(self._in_event_shape)])
        return _op("reshape_fldj",
                   lambda v: jnp.zeros(batch, dtype=v.dtype), x)

    def _forward_shape(self, shape):
        if len(shape) < len(self._in_event_shape):
            raise ValueError("shape rank is smaller than in_event_shape rank")
        if tuple(shape[len(shape) - len(self._in_event_shape):]) != \
                self._in_event_shape:
            raise ValueError(
                f"shape suffix {shape} does not match in_event_shape "
                f"{self._in_event_shape}")
        return tuple(shape[:len(shape) - len(self._in_event_shape)]) + \
            self._out_event_shape

    def _inverse_shape(self, shape):
        if len(shape) < len(self._out_event_shape):
            raise ValueError("shape rank is smaller than out_event_shape rank")
        if tuple(shape[len(shape) - len(self._out_event_shape):]) != \
                self._out_event_shape:
            raise ValueError(
                f"shape suffix {shape} does not match out_event_shape "
                f"{self._out_event_shape}")
        return tuple(shape[:len(shape) - len(self._out_event_shape)]) + \
            self._in_event_shape

    @property
    def _domain(self):
        return variable.Independent(variable.real,
                                    len(self._in_event_shape))

    @property
    def _codomain(self):
        return variable.Independent(variable.real,
                                    len(self._out_event_shape))


class SigmoidTransform(Transform):
    r"""y = sigmoid(x) (ref ``transform.py:900``)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        return _op("sigmoid_fwd", jax.nn.sigmoid, x)

    def _inverse(self, y):
        return _op("sigmoid_inv", lambda v: jnp.log(v) - jnp.log1p(-v), y)

    def _forward_log_det_jacobian(self, x):
        return _op("sigmoid_fldj",
                   lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v), x)

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.Variable(False, 0, constraint.Range(0.0, 1.0))


class SoftmaxTransform(Transform):
    r"""Softmax onto the simplex; not bijective, so log-det-Jacobian is
    undefined (ref ``transform.py:943``)."""

    _type = Type.OTHER

    def _forward(self, x):
        def fn(v):
            z = jnp.exp(v - jnp.max(v, axis=-1, keepdims=True))
            return z / jnp.sum(z, axis=-1, keepdims=True)
        return _op("softmax_fwd", fn, x)

    def _inverse(self, y):
        return _op("softmax_inv", jnp.log, y)

    def _forward_shape(self, shape):
        if len(shape) < 1:
            raise ValueError("input shape must have at least one dimension")
        return shape

    def _inverse_shape(self, shape):
        if len(shape) < 1:
            raise ValueError("input shape must have at least one dimension")
        return shape

    @property
    def _domain(self):
        return variable.Independent(variable.real, 1)

    @property
    def _codomain(self):
        return variable.Variable(False, 1, constraint.simplex)


class StackTransform(Transform):
    r"""Applies a sequence of transforms to each slice along ``axis``
    (ref ``transform.py:999``)."""

    def __init__(self, transforms, axis=0):
        if not isinstance(transforms, (list, tuple)) or not all(
                isinstance(t, Transform) for t in transforms):
            raise TypeError(
                "transforms must be a list/tuple of Transform instances")
        if not isinstance(axis, int):
            raise TypeError("axis must be an int")
        self._transforms = tuple(transforms)
        self._axis = axis
        super().__init__()

    def _is_injective(self):
        return all(t._is_injective() for t in self._transforms)

    @property
    def transforms(self):
        return self._transforms

    @property
    def axis(self):
        return self._axis

    def _check_size(self, v):
        if v.shape[self._axis] != len(self._transforms):
            raise ValueError(
                f"input size along axis {self._axis} "
                f"({v.shape[self._axis]}) must equal the number of "
                f"transforms ({len(self._transforms)})")

    def _map(self, name, v, method):
        v = _t(v)
        self._check_size(v)

        def fn(val):
            cols = []
            for i, t in enumerate(self._transforms):
                out = method(t, Tensor(jnp.take(val, i, axis=self._axis)))
                cols.append(out._value if isinstance(out, Tensor)
                            else jnp.asarray(out))
            return jnp.stack(cols, axis=self._axis)

        return apply_op(name, fn, [v])

    def _forward(self, x):
        return self._map("stack_fwd", x, lambda t, s: t.forward(s))

    def _inverse(self, y):
        return self._map("stack_inv", y, lambda t, s: t.inverse(s))

    def _forward_log_det_jacobian(self, x):
        return self._map("stack_fldj", x,
                         lambda t, s: t.forward_log_det_jacobian(s))

    @property
    def _domain(self):
        return variable.Stack([t._domain for t in self._transforms],
                              self._axis)

    @property
    def _codomain(self):
        return variable.Stack([t._codomain for t in self._transforms],
                              self._axis)


class StickBreakingTransform(Transform):
    r"""Maps an unconstrained (K-1)-vector to a K-simplex by stick-breaking
    (ref ``transform.py:1104``)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        def fn(v):
            offset = v.shape[-1] + 1 - jnp.arange(1, v.shape[-1] + 1)
            z = jax.nn.sigmoid(v - jnp.log(offset.astype(v.dtype)))
            zc = jnp.cumprod(1 - z, axis=-1)
            pad = [(0, 0)] * (v.ndim - 1) + [(0, 1)]
            return jnp.pad(z, pad, constant_values=1.0) * \
                jnp.pad(zc, [(0, 0)] * (v.ndim - 1) + [(1, 0)],
                        constant_values=1.0)
        return _op("stickbreaking_fwd", fn, x)

    def _inverse(self, y):
        def fn(v):
            y_crop = v[..., :-1]
            offset = v.shape[-1] - jnp.arange(1, y_crop.shape[-1] + 1)
            sf = 1.0 - jnp.cumsum(y_crop, axis=-1)
            x = jnp.log(y_crop / sf) + jnp.log(offset.astype(v.dtype))
            return x
        return _op("stickbreaking_inv", fn, y)

    def _forward_log_det_jacobian(self, x):
        def fn(v):
            y = self._forward(Tensor(v))._value
            offset = v.shape[-1] + 1 - jnp.arange(1, v.shape[-1] + 1)
            z = v - jnp.log(offset.astype(v.dtype))
            return jnp.sum(-z + jax.nn.log_sigmoid(z) +
                           jnp.log(y[..., :-1]), axis=-1)
        return _op("stickbreaking_fldj", fn, x)

    def _forward_shape(self, shape):
        if not shape:
            raise ValueError("input shape must have at least one dimension")
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def _inverse_shape(self, shape):
        if not shape:
            raise ValueError("input shape must have at least one dimension")
        return tuple(shape[:-1]) + (shape[-1] - 1,)

    @property
    def _domain(self):
        return variable.Independent(variable.real, 1)

    @property
    def _codomain(self):
        return variable.Variable(False, 1, constraint.simplex)


class TanhTransform(Transform):
    r"""y = tanh(x) (ref ``transform.py:1169``)."""

    _type = Type.BIJECTION

    def _forward(self, x):
        return _op("tanh_fwd", jnp.tanh, x)

    def _inverse(self, y):
        return _op("tanh_inv", jnp.arctanh, y)

    def _forward_log_det_jacobian(self, x):
        # 2 (log 2 - x - softplus(-2x)): higher precision than
        # -log1p(-tanh(x)^2) (ref transform.py:1216-1222)
        return _op("tanh_fldj",
                   lambda v: 2.0 * (math.log(2.0) - v -
                                    jax.nn.softplus(-2.0 * v)), x)

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.Variable(False, 0, constraint.Range(-1.0, 1.0))
