"""Value constraints for random variables (ref
``python/paddle/distribution/constraint.py:17-52``)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


class Constraint:
    """Constraint condition for random variable (ref ``constraint.py:17``)."""

    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        return apply_op("constraint_real", lambda v: v == v, [_t(value)])


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper
        super().__init__()

    def __call__(self, value):
        return apply_op(
            "constraint_range",
            lambda v: (self._lower <= v) & (v <= self._upper), [_t(value)])


class Positive(Constraint):
    def __call__(self, value):
        return apply_op("constraint_positive", lambda v: v >= 0.0,
                        [_t(value)])


class Simplex(Constraint):
    def __call__(self, value):
        def fn(v):
            return jnp.all(v >= 0, axis=-1) & (
                jnp.abs(jnp.sum(v, axis=-1) - 1.0) < 1e-6)
        return apply_op("constraint_simplex", fn, [_t(value)])


real = Real()
positive = Positive()
simplex = Simplex()
