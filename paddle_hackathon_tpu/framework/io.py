"""paddle.save / paddle.load.

Ref ``python/paddle/framework/io.py:574,791`` — the reference pickles a nested
state_dict of numpy-ified tensors. Same wire idea here, but arrays are stored
in an npz member next to a pickled skeleton so loads are zero-copy into numpy
(and the pickle never contains executable array payloads).
"""

from __future__ import annotations

import io as _io
import os
import pickle
import zipfile

import numpy as np

from ..core.tensor import Tensor
from ..nn.parameter import Parameter

_MAGIC = "paddle_hackathon_tpu.save.v1"


def _disassemble(obj, arrays, path=""):
    if isinstance(obj, Tensor):
        key = f"t{len(arrays)}"
        arrays[key] = np.asarray(obj._value)
        return {"__tensor__": key,
                "__param__": isinstance(obj, Parameter),
                "name": obj.name,
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _disassemble(v, arrays, f"{path}.{k}") for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_disassemble(v, arrays, f"{path}[{i}]") for i, v in enumerate(obj)]
        return {"__seq__": type(obj).__name__, "items": out}
    return obj


def _reassemble(obj, arrays):
    if isinstance(obj, dict):
        if "__tensor__" in obj:
            arr = arrays[obj["__tensor__"]]
            if obj.get("__param__"):
                t = Parameter(arr, name=obj.get("name"))
                t.stop_gradient = obj.get("stop_gradient", False)
                return t
            t = Tensor(arr, stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name")
            return t
        if "__seq__" in obj:
            seq = [_reassemble(v, arrays) for v in obj["items"]]
            return tuple(seq) if obj["__seq__"] == "tuple" else seq
        return {k: _reassemble(v, arrays) for k, v in obj.items()}
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save equivalent — state_dicts, nested dicts/lists of Tensors,
    and plain picklable python objects."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {}
    skeleton = _disassemble(obj, arrays)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr("MAGIC", _MAGIC)
        zf.writestr("skeleton.pkl", pickle.dumps(skeleton, protocol=protocol))
        buf = _io.BytesIO()
        np.savez(buf, **arrays)
        zf.writestr("arrays.npz", buf.getvalue())


def load(path, **configs):
    """paddle.load equivalent."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with zipfile.ZipFile(path, "r") as zf:
        magic = zf.read("MAGIC").decode()
        if magic != _MAGIC:
            raise ValueError(f"not a paddle_hackathon_tpu checkpoint: {path}")
        skeleton = pickle.loads(zf.read("skeleton.pkl"))
        with zf.open("arrays.npz") as f:
            npz = np.load(_io.BytesIO(f.read()))
            arrays = {k: npz[k] for k in npz.files}
    return _reassemble(skeleton, arrays)
