"""Framework-level utilities: save/load, in_dynamic_mode, etc."""

from .io import load, save  # noqa: F401


def in_dynamic_mode() -> bool:
    """True when executing eagerly (not inside a to_static trace)."""
    try:
        from ..jit import _trace_state
        return not getattr(_trace_state, "tracing", False)
    except ImportError:
        return True


def in_dygraph_mode() -> bool:
    return in_dynamic_mode()
