"""paddle.io equivalent — Dataset / Sampler / DataLoader.

Ref ``python/paddle/io/`` + ``fluid/reader.py:275`` (DataLoader),
``fluid/dataloader/dataloader_iter.py:148,342``. The reference feeds GPUs with
worker *processes* + shared-memory tensports; on TPU the input path is
host-side numpy → a background-thread prefetch pipeline that overlaps batch
assembly with device compute, then one device_put per batch (PJRT pins and
DMAs). A native C++ ring buffer backs the prefetcher when built (see
``native/``).
"""

from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,  # noqa: F401
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,  # noqa: F401
                      Sampler, SequenceSampler, SubsetRandomSampler,
                      WeightedRandomSampler)
from .dataloader import (DataLoader, default_collate_fn, device_prefetch,  # noqa: F401
                         get_worker_info)
from .transfer import TransferRing, finish_d2h, start_d2h  # noqa: F401
