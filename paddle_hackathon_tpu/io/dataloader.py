"""DataLoader (ref ``fluid/reader.py:275`` DataLoader;
``fluid/dataloader/dataloader_iter.py`` single/multi-process iterators).

TPU-native design: batches are assembled on the host by a pool of worker
threads feeding a bounded prefetch queue (the reference uses worker processes +
shared-memory because CUDA pins per-process memory; PJRT transfers are
zero-copy from numpy so threads suffice — numpy/image decode releases the
GIL). ``prefetch_factor`` batches are kept in flight, overlapping input
assembly with device compute like the reference's ``buffered_reader.cc``.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    """Stack samples into batched Tensors (ref
    ``fluid/dataloader/collate.py`` default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([s._value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([s[i] for s in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    raise TypeError(f"cannot collate type {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)
        self._no_batch = batch_size is None

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset DataLoader is unknown")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return iter(_PrefetchIter(self))

    def _iter_single(self):
        for batch_idx in self.batch_sampler:
            samples = [self.dataset[i] for i in batch_idx]
            if self._no_batch:
                yield samples[0]
            else:
                yield self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == (self.batch_size or 1):
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)


class _PrefetchIter:
    """Thread-pool prefetching iterator (ref
    ``_DataLoaderIterMultiProcess`` ``dataloader_iter.py:342``: outstanding
    batch queue + in-order reordering)."""

    _SENTINEL = object()

    def __init__(self, loader: DataLoader):
        self.loader = loader
        self.batches = list(loader.batch_sampler)
        self.max_outstanding = loader.num_workers * loader.prefetch_factor
        self.task_q: "queue.Queue" = queue.Queue()
        self.results = {}
        self.next_emit = 0
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.error = None
        for i, b in enumerate(self.batches):
            self.task_q.put((i, b))
        self.n_tasks = len(self.batches)
        self.workers = []
        for wid in range(loader.num_workers):
            t = threading.Thread(target=self._worker, args=(wid,), daemon=True)
            t.start()
            self.workers.append(t)

    def _worker(self, wid):
        _worker_info.info = WorkerInfo(wid, self.loader.num_workers,
                                       self.loader.dataset)
        if self.loader.worker_init_fn is not None:
            self.loader.worker_init_fn(wid)
        while True:
            try:
                i, idxs = self.task_q.get_nowait()
            except queue.Empty:
                return
            try:
                samples = [self.loader.dataset[j] for j in idxs]
                batch = self.loader.collate_fn(samples)
            except Exception as e:  # propagate to consumer
                with self.cv:
                    self.error = e
                    self.cv.notify_all()
                return
            with self.cv:
                while i > self.next_emit + self.max_outstanding and self.error is None:
                    self.cv.wait(timeout=1.0)
                self.results[i] = batch
                self.cv.notify_all()

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_emit >= self.n_tasks:
            raise StopIteration
        with self.cv:
            while self.next_emit not in self.results and self.error is None:
                self.cv.wait(timeout=1.0)
            if self.error is not None:
                raise self.error
            batch = self.results.pop(self.next_emit)
            self.next_emit += 1
            self.cv.notify_all()
        return batch
