"""DataLoader (ref ``fluid/reader.py:275`` DataLoader;
``fluid/dataloader/dataloader_iter.py`` single/multi-process iterators).

TPU-native design: batches are assembled on the host by a pool of worker
threads feeding a bounded prefetch queue (PJRT transfers are zero-copy
from numpy, and numpy/image decode releases the GIL, so threads cover
the numpy-bound case). For PYTHON-heavy per-sample transforms — which
serialize on the GIL — ``use_process_workers=True`` switches to worker
processes with shared-memory batch transfer, the reference's
``dataloader_iter.py:342`` + ``worker.py`` design. ``prefetch_factor``
batches are kept in flight, overlapping input assembly with device
compute like the reference's ``buffered_reader.cc``.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from ..observability.sanitizers import make_lock, share_object
from .dataset import IterableDataset
from .sampler import BatchSampler

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    """Stack samples into batched Tensors (ref
    ``fluid/dataloader/collate.py`` default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([s._value for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([s[i] for s in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    raise TypeError(f"cannot collate type {type(sample)}")


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_buffer_reader = use_buffer_reader
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_shared_memory = use_shared_memory
        self.use_process_workers = use_process_workers
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)
        self._no_batch = batch_size is None

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset DataLoader is unknown")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        if self.use_process_workers:
            return iter(_ProcPrefetchIter(self))
        if self.use_buffer_reader:
            from ..core import native
            if native.available():
                return iter(_BufferedPrefetchIter(self))
        return iter(_PrefetchIter(self))

    def _iter_single(self):
        for batch_idx in self.batch_sampler:
            samples = [self.dataset[i] for i in batch_idx]
            if self._no_batch:
                yield samples[0]
            else:
                yield self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == (self.batch_size or 1):
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)


def device_prefetch(iterator, size=2, device=None):
    """Device-prefetch iterator (ref ``buffered_reader.cc``'s H2D staging
    stage): pull up to ``size`` batches ahead of the consumer and start
    their host→device transfers immediately.  ``jax.device_put`` is
    asynchronous, so the copies overlap device compute — the consumer
    (e.g. ``Model.fit``'s compiled trainer) finds its next batch already
    resident instead of paying H2D on the critical path.

    numpy leaves are ``device_put``; jax arrays and Tensors pass through
    (already resident or in flight).  Works on any iterator of (nested)
    batches — tuples/lists/dicts of arrays.

    Each host-side pull is timed into the
    ``input_wait_seconds{site=device_prefetch}`` histogram: when the
    consumer outruns the producer, this distribution fattening is the
    input-starvation signal (docs/OBSERVABILITY.md).
    """
    import time as _time

    import jax

    from ..observability import metrics as _obs
    wait_hist = _obs.get_registry().histogram(
        "input_wait_seconds",
        "host wait per batch pulled from the input pipeline",
        unit="s").labels(site="device_prefetch")

    def _put_leaf(a):
        if isinstance(a, Tensor):
            return a
        if isinstance(a, np.ndarray) and a.dtype.kind not in "OUSV":
            return jax.device_put(a, device)
        return a

    def _put(batch):
        return jax.tree.map(_put_leaf, batch,
                            is_leaf=lambda t: isinstance(t, Tensor))

    from ..observability import faults as _faults
    from .transfer import TransferRing

    it = iter(iterator)
    size = max(int(size), 1)
    # a buffer of ``size`` batches = ``size - 1`` still in flight after
    # each yield (the ring pops the oldest once it is over depth)
    ring = TransferRing(depth=size - 1)
    while True:
        try:
            # drill point for the crash harness: a dataloader dying
            # (or stalling) mid-fit is a canonical training failure
            _faults.point("io.prefetch")
            t0 = _time.perf_counter()
            nxt = next(it)
            wait_hist.observe(_time.perf_counter() - t0)
        except StopIteration:
            for b in ring.drain():
                yield b
            return
        ready = ring.push(_put(nxt))
        if ready is not None:
            yield ready


class _PrefetchIter:
    """Thread-pool prefetching iterator (ref
    ``_DataLoaderIterMultiProcess`` ``dataloader_iter.py:342``: outstanding
    batch queue + in-order reordering)."""

    _SENTINEL = object()

    def __init__(self, loader: DataLoader):
        self.loader = loader
        self.batches = list(loader.batch_sampler)
        self.max_outstanding = loader.num_workers * loader.prefetch_factor
        self.task_q: "queue.Queue" = queue.Queue()
        self.results = {}
        self.next_emit = 0
        self.lock = make_lock("dataloader.prefetch")
        self.cv = threading.Condition(self.lock)
        self.error = None
        for i, b in enumerate(self.batches):
            self.task_q.put((i, b))
        self.n_tasks = len(self.batches)
        self.workers = []
        # declare shared BEFORE the workers start: every worker access
        # from here on is lockset-checked when the race sanitizer is
        # armed (zero cost otherwise — share_object returns self as-is)
        share_object(self, "dataloader.prefetch")
        for wid in range(loader.num_workers):
            t = threading.Thread(target=self._worker, args=(wid,), daemon=True)
            t.start()
            self.workers.append(t)

    def _worker(self, wid):
        _worker_info.info = WorkerInfo(wid, self.loader.num_workers,
                                       self.loader.dataset)
        if self.loader.worker_init_fn is not None:
            self.loader.worker_init_fn(wid)
        while True:
            try:
                i, idxs = self.task_q.get_nowait()
            except queue.Empty:
                return
            try:
                samples = [self.loader.dataset[j] for j in idxs]
                batch = self.loader.collate_fn(samples)
            except Exception as e:  # propagate to consumer
                with self.cv:
                    self.error = e
                    self.cv.notify_all()
                return
            with self.cv:
                while i > self.next_emit + self.max_outstanding and self.error is None:
                    self.cv.wait(timeout=1.0)
                self.results[i] = batch
                self.cv.notify_all()

    def __iter__(self):
        return self

    def __next__(self):
        with self.cv:
            # the drained check must read next_emit UNDER the cv: it is
            # written under the cv below, and two consumer threads (or
            # the buffered stager racing a direct consumer) checking it
            # lock-free could both pass and one would wait forever on a
            # batch the other already emitted (PHT009 check-then-act)
            if self.next_emit >= self.n_tasks:
                raise StopIteration
            while self.next_emit not in self.results and self.error is None:
                self.cv.wait(timeout=1.0)
            if self.error is not None:
                raise self.error
            batch = self.results.pop(self.next_emit)
            self.next_emit += 1
            self.cv.notify_all()
        return batch


def _np_collate(batch):
    """Numpy-only collate for worker PROCESSES: the default collate
    builds jax arrays, but a forked child must not call into XLA (its
    runtime threads do not survive fork) — the parent re-wraps the
    numpy leaves into Tensors after transport."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate([s[i] for s in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _np_collate([s[k] for s in batch]) for k in sample}
    raise TypeError(
        f"cannot collate type {type(sample)} in a worker process; "
        "datasets used with use_process_workers=True must yield "
        "numpy/scalar/list/dict samples (jax arrays cannot cross fork)")


def _proc_worker(dataset, collate_fn, worker_init_fn, wid, num_workers,
                 task_q, data_q, use_shm):
    """Worker-process body (ref ``fluid/dataloader/worker.py``
    ``_worker_loop``): fetch index batches from ``task_q``, collate, ship
    results back — numeric arrays through shared memory when ``use_shm``
    (the reference's shared-memory tensor transfer), everything else
    pickled on the queue."""
    import traceback
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    while True:
        task = task_q.get()
        if task is None:
            return
        i, idxs = task
        try:
            batch = collate_fn([dataset[j] for j in idxs])
            arrays, structure = _flatten_batch(batch)
            metas = []
            for a in arrays:
                if use_shm and a.dtype.kind not in "OUSV" and a.nbytes > 0:
                    from multiprocessing import (resource_tracker,
                                                 shared_memory)
                    shm = shared_memory.SharedMemory(create=True,
                                                     size=a.nbytes)
                    np.ndarray(a.shape, a.dtype, buffer=shm.buf)[...] = a
                    metas.append(("shm", shm.name, a.dtype.str, a.shape))
                    shm.close()
                    # ownership transfers to the parent (which unlinks
                    # after copying): drop this process's tracker
                    # registration, or the tracker double-cleans (noise)
                    # — and a worker-private tracker (possible if the
                    # fork predated the parent's tracker) would unlink
                    # segments the parent has not read yet on worker exit
                    try:
                        resource_tracker.unregister(
                            shm._name, "shared_memory")
                    except Exception:
                        pass
                else:
                    metas.append(("raw", a))
            data_q.put((i, metas, structure))
        except Exception as e:  # noqa: BLE001 — relayed to the parent
            data_q.put(("error", f"{type(e).__name__}: {e}\n"
                                 f"{traceback.format_exc(limit=8)}", None))
            return


class _ProcPrefetchIter:
    """Worker-PROCESS prefetching iterator (ref
    ``_DataLoaderIterMultiProcess`` ``dataloader_iter.py:342``): index
    batches fan out to worker processes; results return in submission
    order through a bounded outstanding-task window.  This is the path
    for Python-heavy (GIL-bound) per-sample transforms — the thread pool
    (`_PrefetchIter`) serializes those on the GIL; processes run them in
    parallel (VERDICT r4 directive #5).

    Start method: a FORKSERVER context is preferred when the worker
    payload (dataset, collate, worker_init_fn) pickles — the server is
    posix_spawn'ed single-threaded, so workers never fork() a
    multi-threaded JAX parent (Python 3.12 deprecates that; forked
    children can also deadlock on locks held by threads that don't
    survive the fork).  When the payload doesn't pickle (closures,
    open handles) the iterator falls back to plain fork(): the dataset
    needn't pickle then, but child-side work MUST stay numpy-only —
    no XLA/jax calls (the runtime threads don't survive the fork; see
    ``_np_collate``).  Numeric batch leaves travel through POSIX shared
    memory either way (one memcpy in the worker, one attach+copy in the
    parent); non-numeric leaves pickle."""

    @staticmethod
    def _pick_context(loader, collate):
        import multiprocessing
        cached = getattr(loader, "_proc_mp_start_method", None)
        if cached is not None:
            return multiprocessing.get_context(cached)
        method = "fork"
        if "forkserver" in multiprocessing.get_all_start_methods():
            # probe picklability through a null sink: no bytes are
            # materialized, so a multi-GB in-memory dataset costs one
            # serialization pass, not a 2x RAM spike
            import io as _io
            import pickle

            class _Null(_io.RawIOBase):
                def writable(self):
                    return True

                def write(self, b):
                    return len(b)

            try:
                pickle.Pickler(_Null(),
                               protocol=pickle.HIGHEST_PROTOCOL).dump(
                    (loader.dataset, collate, loader.worker_init_fn))
                method = "forkserver"
            except Exception:  # unpicklable payload: fork keeps working
                pass
        loader._proc_mp_start_method = method  # probe once per loader
        return multiprocessing.get_context(method)

    def __init__(self, loader: DataLoader):
        self.loader = loader
        collate = (loader.collate_fn
                   if loader.collate_fn is not default_collate_fn
                   else _np_collate)
        ctx = self._pick_context(loader, collate)
        if loader.use_shared_memory:
            # spawn the resource tracker BEFORE forking: children must
            # inherit the parent's tracker, not spawn private ones whose
            # exit-cleanup unlinks segments the parent still needs
            from multiprocessing import resource_tracker
            resource_tracker.ensure_running()
        self.batches = list(loader.batch_sampler)
        self.n_tasks = len(self.batches)
        self.max_outstanding = max(
            loader.num_workers * loader.prefetch_factor, 1)
        self.task_q = ctx.Queue()
        self.data_q = ctx.Queue()
        self.results = {}
        self.next_emit = 0
        self.next_task = 0
        # close() runs from the consumer AND from __del__ (which the GC
        # may fire on any thread): the closed check-then-set must be
        # atomic or both callers race past it (PHT010's shape) and
        # double-drain the queues
        self._close_lock = make_lock("dataloader.close")
        self._closed = False
        self.workers = [
            ctx.Process(target=_proc_worker,
                        args=(loader.dataset, collate,
                              loader.worker_init_fn, wid,
                              loader.num_workers, self.task_q, self.data_q,
                              loader.use_shared_memory),
                        daemon=True)
            for wid in range(loader.num_workers)]
        for w in self.workers:
            w.start()
        while (self.next_task < self.n_tasks
               and self.next_task < self.max_outstanding):
            self._submit()

    def _submit(self):
        self.task_q.put((self.next_task, self.batches[self.next_task]))
        self.next_task += 1

    def _reconstruct(self, metas, structure):
        from multiprocessing import shared_memory

        import jax.numpy as jnp
        arrays = []
        for meta in metas:
            if meta[0] == "raw":
                a = meta[1]
                arrays.append(Tensor(jnp.asarray(a))
                              if isinstance(a, np.ndarray)
                              and a.dtype.kind not in "OUSV" else a)
                continue
            _, name, dtype, shape = meta
            shm = shared_memory.SharedMemory(name=name)
            try:
                view = np.ndarray(shape, np.dtype(dtype), buffer=shm.buf)
                arrays.append(Tensor(jnp.asarray(view.copy())))
            finally:
                shm.close()
                shm.unlink()
        return _unflatten_batch(arrays, structure)

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_emit >= self.n_tasks:
            self.close()
            raise StopIteration
        timeout = self.loader.timeout or None
        while self.next_emit not in self.results:
            try:
                item = self.data_q.get(
                    timeout=timeout if timeout else 5.0)
            except Exception:
                if timeout:
                    self.close()
                    raise RuntimeError(
                        f"DataLoader worker timed out after {timeout}s")
                # a worker killed mid-task (OOM/segfault) never delivers
                # its batch — waiting for the rest would hang forever
                dead = [w for w in self.workers
                        if w.exitcode not in (None, 0)]
                if dead:
                    codes = [w.exitcode for w in dead]
                    self.close()
                    raise RuntimeError(
                        f"DataLoader worker process(es) died "
                        f"(exitcode {codes}); their in-flight batches "
                        "are lost") from None
                if not any(w.is_alive() for w in self.workers):
                    self.close()
                    raise RuntimeError(
                        "all DataLoader worker processes exited "
                        "unexpectedly") from None
                continue
            if item[0] == "error":
                self.close()
                raise RuntimeError(
                    f"DataLoader worker raised:\n{item[1]}")
            i, metas, structure = item
            self.results[i] = (metas, structure)
        metas, structure = self.results.pop(self.next_emit)
        self.next_emit += 1
        if self.next_task < self.n_tasks:
            self._submit()
        elif self.next_emit >= self.n_tasks:
            for _ in self.workers:
                self.task_q.put(None)  # drain workers at epoch end
        return self._reconstruct(metas, structure)

    def close(self):
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        # graceful first: sentinels let each worker finish its CURRENT
        # task and flush its queue feeder — terminating straight away
        # would strand in-flight shm segments that no process can name
        # anymore (the worker already unregistered them)
        for _ in self.workers:
            self.task_q.put(None)
        pending = list(self.results.values())
        self.results.clear()
        import queue as _q
        import time as _time
        deadline = _time.monotonic() + 5.0
        while (any(w.is_alive() for w in self.workers)
               and _time.monotonic() < deadline):
            try:
                item = self.data_q.get(timeout=0.1)
            except _q.Empty:
                continue
            if item and not isinstance(item[0], str):
                pending.append((item[1], item[2]))
        for w in self.workers:
            if w.is_alive():
                w.terminate()
            w.join()
        # final drain after join: everything the feeders flushed
        while True:
            try:
                item = self.data_q.get_nowait()
            except Exception:
                break
            if item and not isinstance(item[0], str):
                pending.append((item[1], item[2]))
        # unlink segments parked in results or undrained in the queue —
        # ownership transferred to the parent; an early-terminated epoch
        # must not leak /dev/shm
        from multiprocessing import shared_memory
        for metas, _ in pending:
            for meta in metas:
                if meta[0] == "shm":
                    try:
                        shm = shared_memory.SharedMemory(name=meta[1])
                        shm.close()
                        shm.unlink()
                    except FileNotFoundError:
                        pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _BufferedPrefetchIter:
    """Prefetch iterator with the native staging ring (ref
    ``operators/reader/buffered_reader.cc``).

    Pipeline: worker threads (dataset fetch + collate, Python) -> stager
    thread (C++ memcpy into recycled slots, GIL released during the copy) ->
    consumer (copies to a device buffer, then recycles the slot).

    Metadata for each batch is queued BEFORE its arrays are staged so the
    consumer can drain slots while the stager fills them — a batch with more
    arrays than ring slots therefore streams through instead of
    deadlocking. Object/str arrays (non-numeric dtypes) bypass the ring and
    travel on the metadata queue directly.
    """

    def __init__(self, loader: DataLoader):
        from ..core import native
        self.inner = _PrefetchIter(loader)
        slot_bytes = 1 << 20
        n_slots = max(4, loader.num_workers * loader.prefetch_factor * 2)
        self.ring = native.StagingRing(n_slots=n_slots, slot_bytes=slot_bytes)
        self.meta_q: "queue.Queue" = queue.Queue()
        # same contract as _ProcPrefetchIter: close() is reachable from
        # the consumer and from GC-driven __del__ concurrently
        self._close_lock = make_lock("dataloader.close")
        self._closed = False
        # the thread target closes over (inner, ring, meta_q) directly — NOT
        # self — so an abandoned iterator can be garbage-collected, firing
        # __del__ -> close() -> ring.close(), which unblocks this thread
        self._stager = threading.Thread(
            target=_stage_loop, args=(self.inner, self.ring, self.meta_q),
            daemon=True)
        self._stager.start()

    def close(self):
        """Unblock and tear down (also called on abandonment via __del__)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self.ring.close()  # unblocks a stager stuck waiting for a free slot
        with self.inner.cv:
            if self.inner.error is None:
                self.inner.error = GeneratorExit("DataLoader iterator closed")
            self.inner.cv.notify_all()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        item = self.meta_q.get()
        if item is None:
            self.close()
            raise StopIteration
        if isinstance(item, Exception):
            self.close()
            raise item
        metas, structure = item
        import jax.numpy as jnp
        import numpy as np
        from ..core.tensor import Tensor
        arrays = []
        for meta in metas:
            if meta[0] == "raw":
                arrays.append(Tensor(jnp.asarray(meta[1]))
                              if np.asarray(meta[1]).dtype.kind not in "OUSV"
                              else meta[1])
                continue
            dtype, shape = meta
            slot, view = self.ring.next(dtype, shape)
            if slot is None:
                self.close()
                raise RuntimeError(
                    "staging ring drained mid-batch (stager failed)")
            # host memcpy BEFORE recycling the slot: a device-side
            # block_until_ready here costs a full round trip per array
            # (through the axon tunnel: ~150 ms, measured 3x the whole
            # epoch), while np.array is a plain memcpy; the fresh host
            # array is never mutated again, so an aliasing CPU backend
            # is safe and the H2D stays async
            host = np.array(view)
            arrays.append(Tensor(jnp.asarray(host)))
            self.ring.release(slot)
        return _unflatten_batch(arrays, structure)


def _stage_loop(inner, ring, meta_q):
    """Stager thread body (module-level: must not keep the iterator alive)."""
    seq = 0
    try:
        for batch in inner:
            arrays, structure = _flatten_batch(batch)
            metas = []
            ringable = []
            for a in arrays:
                if a.dtype.kind in "OUSV":  # object/str: bypass ring
                    metas.append(("raw", a))
                else:
                    metas.append((a.dtype, a.shape))
                    ringable.append(a)
            # meta first: the consumer starts draining slots while the
            # arrays stream through the ring (no capacity deadlock)
            meta_q.put((metas, structure))
            for a in ringable:
                if ring.stage(a, seq) < 0:
                    raise RuntimeError("staging ring closed mid-epoch")
                seq += 1
        meta_q.put(None)
    except Exception as e:
        meta_q.put(e)
    except BaseException:  # GeneratorExit from close(): silent exit
        meta_q.put(None)
    finally:
        ring.close()


def _flatten_batch(batch):
    """Split a collated batch into (list of numpy arrays, structure)."""
    import numpy as np
    from ..core.tensor import Tensor
    if isinstance(batch, dict):
        arrays, struct = [], []
        for k in batch:
            a, s = _flatten_batch(batch[k])
            struct.append((k, len(a), s))
            arrays.extend(a)
        return arrays, ("dict", struct)
    if isinstance(batch, (list, tuple)):
        arrays, struct = [], []
        for item in batch:
            a, s = _flatten_batch(item)
            struct.append((len(a), s))
            arrays.extend(a)
        return arrays, (type(batch).__name__, struct)
    if isinstance(batch, Tensor):
        return [np.asarray(batch.numpy())], "tensor"
    return [np.asarray(batch)], "array"


def _unflatten_batch(arrays, structure):
    if structure in ("tensor", "array"):
        return arrays[0]
    kind, struct = structure
    if kind == "dict":
        out = {}
        i = 0
        for k, n, s in struct:
            out[k] = _unflatten_batch(arrays[i:i + n], s)
            i += n
        return out
    out = []
    i = 0
    for n, s in struct:
        out.append(_unflatten_batch(arrays[i:i + n], s))
        i += n
    return tuple(out) if kind == "tuple" else out
