"""Datasets (ref ``python/paddle/io/dataloader/dataset.py``)."""

from __future__ import annotations

import bisect
from typing import List, Sequence


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = list(tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    import numpy as np
    from ..core import random as core_random
    import jax
    if sum(lengths) != len(dataset):
        # fraction support
        if all(0 < l < 1 for l in lengths):
            n = len(dataset)
            lengths = [int(l * n) for l in lengths]
            lengths[-1] = n - sum(lengths[:-1])
        else:
            raise ValueError("sum of lengths must equal dataset size")
    key = core_random.split_key()
    perm = np.asarray(jax.random.permutation(key, len(dataset)))
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out
