"""Depth-bounded in-order async-transfer ring.

The overlap pattern ``device_prefetch`` has always used — start a
transfer, keep consuming only once ``depth`` more are in flight, pop in
FIFO order — generalized so the dataloader's h2d staging and the
ZeRO-offload optimizer pipe (``parallel.offload``) share one
implementation instead of two copies of the same deque loop.

The ring itself never touches device APIs: entries are opaque handles
for *already started* work (a ``jax.device_put`` result, a
``copy_to_host_async``'d array, a (key, arrays) tuple...).  ``push``
returns the oldest entry once more than ``depth`` are outstanding —
the caller then performs whatever blocking completion step the entry
needs (``np.asarray``, feeding a jit, yielding a batch) while the
younger transfers stream underneath.

Donation safety: the ring holds a strong reference to every pushed
entry until it is popped, so a buffer handed to an async copy cannot
be garbage-collected (and its storage donated/reused by a jitted call)
while the DMA is still in flight.
"""

from __future__ import annotations

import collections

import jax
import numpy as np

__all__ = ["TransferRing", "start_d2h", "finish_d2h"]


class TransferRing:
    """FIFO pipeline of in-flight transfers, at most ``depth`` deep.

    ``depth=1`` is classic double-buffering (one transfer hides behind
    one completion); ``depth=0`` degenerates to fully synchronous
    (``push`` returns its own argument) so callers can expose the knob
    without branching.
    """

    def __init__(self, depth: int = 1):
        self._depth = max(int(depth), 0)
        self._buf = collections.deque()

    @property
    def depth(self) -> int:
        return self._depth

    def __len__(self) -> int:
        return len(self._buf)

    def push(self, entry):
        """Enqueue a started transfer; returns the oldest entry when the
        ring is over depth (the caller completes it), else ``None``."""
        self._buf.append(entry)
        if len(self._buf) > self._depth:
            return self._buf.popleft()
        return None

    def drain(self):
        """Yield the remaining in-flight entries, oldest first."""
        while self._buf:
            yield self._buf.popleft()


def start_d2h(tree):
    """Kick off device→host copies for every ``jax.Array`` leaf (PJRT
    ``copy_to_host_async``) without blocking; returns ``tree`` unchanged
    so it can ride through a ``TransferRing``."""
    for a in jax.tree.leaves(tree):
        if isinstance(a, jax.Array):
            try:
                a.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # backend without async d2h: finish_d2h still works
    return tree


def finish_d2h(tree):
    """Materialize a (previously ``start_d2h``'d) tree as host numpy —
    the only blocking step of the d2h pipe."""
    return jax.tree.map(
        lambda a: np.asarray(a) if isinstance(a, jax.Array) else a, tree)
