"""Version tolerance for the two jax APIs whose spelling moved.

The repo targets the current jax surface (``jax.shard_map`` with
``axis_names``/``check_vma``, ambient-mesh ``jax.set_mesh``).  Older jax
(0.4.x — the pinned toolchain on some build hosts) ships the same
capabilities under the previous names: ``jax.experimental.shard_map``
with ``(mesh, check_rep, auto)``, and no ambient-mesh context (the mesh
rides explicitly on every shard_map / NamedSharding).  These two helpers
present the new surface on both, so call sites stay written against the
current API.
"""

from __future__ import annotations

import contextlib

import jax

try:
    from jax import shard_map as _shard_map_new
    _NEW_SHARD_MAP = True
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map_old
    _NEW_SHARD_MAP = False

try:
    jax.export
except AttributeError:
    import jax.export  # registers the jax.export submodule on old jax


def shard_map(fn, *, mesh=None, in_specs, out_specs, axis_names=None,
              check_vma=False):
    if _NEW_SHARD_MAP:
        kw = dict(in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if mesh is not None:
            kw["mesh"] = mesh
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return _shard_map_new(fn, **kw)
    if mesh is None:
        from ..parallel.api import get_mesh
        mesh = get_mesh()
    if mesh is None:
        raise ValueError(
            "jax<0.6 shard_map needs an explicit mesh (no ambient-mesh "
            "context exists to read one from)")
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    if auto:
        # Partial-manual regions are beyond old jax: axis_index lowers to
        # an unpartitionable PartitionId, and collectives (ppermute/psum)
        # hit `Check failed: target.IsManualSubgroup()` — a C++ CHECK that
        # ABORTS the process.  Refuse up front with a Python error
        # instead of letting XLA kill the interpreter.
        raise NotImplementedError(
            "partial-manual shard_map (manual "
            f"{sorted(frozenset(axis_names))} over mesh "
            f"{sorted(mesh.axis_names)}) requires jax>=0.6; on this jax "
            "it hard-aborts XLA's SPMD partitioner")
    return _shard_map_old(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, auto=auto)


def set_mesh(mesh):
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    # old jax: the Mesh object itself is the context manager (physical
    # ambient mesh); None callers get a no-op context
    return mesh if mesh is not None else contextlib.nullcontext()
