"""Global RNG state.

Equivalent of the reference's generator machinery (``paddle/phi/core/generator.h``
and ``paddle.seed``). JAX PRNG is functional (explicit keys); to present Paddle's
stateful API we keep a process-global key that stateful ops split from. Under a
jit trace (``to_static`` / functional training steps) stateful splitting would
bake a constant key into the compiled program, so traced programs thread an
explicit key through :func:`rng_scope` — the same design as the reference's
``get_rng_state_tracker`` used by tensor-parallel dropout
(``fleet/meta_parallel/parallel_layers/random.py``).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax

from ..observability.sanitizers import make_lock

_state = threading.local()
_GLOBAL_SEED = 0
_global_key = None
# make_lock: visible to the lock-order/race sanitizers (PHT009 sweep)
_lock = make_lock("core.random")


def seed(s: int) -> None:
    """paddle.seed equivalent: reset the global generator."""
    global _GLOBAL_SEED, _global_key
    # build the key OUTSIDE the lock: jax.random.key dispatches device
    # work, and holding _lock across it stalls every concurrent
    # split_key() behind the device (pht-lint PHT003)
    key = jax.random.key(int(s))
    with _lock:
        _GLOBAL_SEED = int(s)
        _global_key = key


def get_rng_state():
    global _global_key
    k = _global_key
    if k is None:
        # stage/commit: dispatch outside the lock, double-check inside
        # (a racing seed()/get_rng_state() wins; this fresh key is
        # dropped) — see seed() for why.  The return value is re-read
        # INSIDE the lock: a concurrent set_rng_state(None) must not
        # make this return None
        fresh = jax.random.key(_GLOBAL_SEED)
        with _lock:
            if _global_key is None:
                _global_key = fresh
            k = _global_key
    return k


def set_rng_state(key) -> None:
    global _global_key
    with _lock:
        _global_key = key


def split_key() -> jax.Array:
    """Return a fresh key, advancing whichever RNG scope is active."""
    scope_key = getattr(_state, "key", None)
    if scope_key is not None:
        # Inside an rng_scope (possibly a jit trace): split the scoped key.
        new_key, sub = jax.random.split(scope_key)
        _state.key = new_key
        return sub
    get_rng_state()   # init staged outside the lock (see seed())
    global _global_key
    with _lock:
        if _global_key is None:
            # a set_rng_state(None) reset landed between the staged
            # init above and this critical section: re-init here (the
            # rare-race path; the dispatch-under-lock is covered by
            # this function's PHT003 baseline entry)
            _global_key = jax.random.key(_GLOBAL_SEED)
        # the split itself MUST stay under the lock: two threads
        # splitting the same key would both return the same "fresh"
        # key.  Baselined (pht-lint PHT003) — this is the eager
        # Paddle-compat path, not a hot path; traced hot paths thread
        # explicit keys via rng_scope instead.
        _global_key, sub = jax.random.split(_global_key)
        return sub


@contextlib.contextmanager
def rng_scope(key: Optional[jax.Array]):
    """Thread an explicit PRNG key through stateful random ops.

    Used by the jit/static path so dropout etc. consume a traced key argument
    instead of baking a constant.
    """
    prev = getattr(_state, "key", None)
    _state.key = key
    try:
        yield
    finally:
        _state.key = prev


class RNGStatesTracker:
    """Named RNG states for tensor-parallel dropout
    (ref ``parallel_layers/random.py`` ``get_rng_state_tracker``): the 'local'
    state differs per model-parallel rank, the 'global' state is identical,
    so dropout masks on sharded activations decorrelate while replicated
    activations stay consistent."""

    def __init__(self):
        self.states = {}

    def add(self, name: str, seed_: int) -> None:
        if name in self.states:
            raise ValueError(f"rng state {name!r} already exists")
        self.states[name] = jax.random.key(int(seed_))

    @contextlib.contextmanager
    def rng_state(self, name: str = "model_parallel_rng"):
        if name not in self.states:
            self.add(name, _GLOBAL_SEED + hash(name) % (2 ** 16))
        key, sub = jax.random.split(self.states[name])
        self.states[name] = key
        with rng_scope(sub):
            yield


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker
