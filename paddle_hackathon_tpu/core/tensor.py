"""The user-facing Tensor.

TPU-native equivalent of the reference's ``paddle::experimental::Tensor``
(``paddle/phi/api/include/tensor.h:83``) + the eager ``AutogradMeta``
(``paddle/fluid/eager/autograd_meta.h``) merged into one Python object: the
payload is a ``jax.Array`` (PJRT owns layout, HBM placement and streams — the
whole of phi/backends + fluid/memory collapses into this), while
``stop_gradient`` / ``_grad_node`` / ``_grad_value`` carry the autograd state.

Most math methods are monkey-patched onto this class by ``ops/__init__.py``,
mirroring how the reference patches ``VarBase``
(``fluid/dygraph/math_op_patch.py:66``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd, device
from .dtype import convert_dtype, default_float_dtype


class Tensor:
    __slots__ = ("_value", "stop_gradient", "name", "persistable",
                 "_grad_node", "_out_idx", "_grad_value", "_grad_hooks",
                 "_process_mesh", "_shard_spec",  # auto_parallel annotations
                 "_lod",  # legacy LoD offsets (static.nn sequence_* ops)
                 "_leaf_alias",  # double-grad snapshot -> original leaf
                 "__weakref__")

    # auto_parallel annotations (set by parallel.auto_parallel.shard_tensor);
    # default None without paying per-construction init cost
    @property
    def process_mesh(self):
        try:
            return self._process_mesh
        except AttributeError:
            return None

    @process_mesh.setter
    def process_mesh(self, value):
        self._process_mesh = value

    @property
    def shard_spec(self):
        try:
            return self._shard_spec
        except AttributeError:
            return None

    @shard_spec.setter
    def shard_spec(self, value):
        self._shard_spec = value

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None,
                 _grad_node=None, _out_idx: int = 0):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.name = name
        self.persistable = False
        self._grad_node = _grad_node
        self._out_idx = _out_idx
        self._grad_value = None
        self._grad_hooks = []

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if devs is None:
            return device.current_place()
        try:
            d = next(iter(self._value.devices()))
            plat = "tpu" if d.platform == "axon" else d.platform
            return device.Place(plat, d.id)
        except Exception:
            return device.current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def numel(self) -> int:
        return self.size

    # -- conversion --------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def astype(self, dtype) -> "Tensor":
        d = convert_dtype(dtype)
        return autograd.apply_op("cast", lambda x: x.astype(d), [self])

    cast = astype

    def _to_jax(self):
        return self._value

    # -- autograd ----------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad_value is None:
            return None
        return Tensor(self._grad_value, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        self._grad_value = None if value is None else (
            value._value if isinstance(value, Tensor) else jnp.asarray(value))

    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False) -> None:
        """Run reverse-mode AD from this tensor (ref ``egr::Backward``,
        ``eager/backward.cc:848``)."""
        if grad_tensor is None:
            g = jnp.ones(self._value.shape, self._value.dtype)
        else:
            g = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
        autograd.run_backward([self], [g], retain_graph=retain_graph)

    def clear_grad(self) -> None:
        self._grad_value = None

    def clear_gradient(self, set_to_zero: bool = False) -> None:
        if set_to_zero and self._grad_value is not None:
            self._grad_value = jnp.zeros_like(self._grad_value)
        else:
            self._grad_value = None

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True, name=self.name)

    def clone(self) -> "Tensor":
        return autograd.apply_op("clone", lambda x: x + 0, [self])

    def register_hook(self, hook) -> "_HookHandle":
        """Gradient hook (ref ``egr::utils::RegisterGradientHookForTensor``)."""
        if self._grad_node is None:
            self._grad_hooks.append(hook)
            return _HookHandle(self._grad_hooks, hook)
        node = self._grad_node
        if node.hooks is None:
            node.hooks = {}
        node.hooks.setdefault(self._out_idx, []).append(hook)
        return _HookHandle(node.hooks[self._out_idx], hook)

    # -- in-place ----------------------------------------------------------
    def _set_value(self, value) -> None:
        """Replace the payload in place (optimizer update path)."""
        self._value = value._value if isinstance(value, Tensor) else value

    def set_value(self, value) -> None:
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value, dtype=self._value.dtype).reshape(self._value.shape)

    def copy_(self, other, blocking: bool = True) -> None:
        self.set_value(other)

    def fill_(self, value) -> "Tensor":
        self._value = jnp.full_like(self._value, value)
        return self

    def zero_(self) -> "Tensor":
        self._value = jnp.zeros_like(self._value)
        return self

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx) -> "Tensor":
        idx = _unwrap_index(idx)
        return autograd.apply_op("slice", lambda x: x[idx], [self])

    def __setitem__(self, idx, value) -> None:
        idx = _unwrap_index(idx)
        if not isinstance(value, Tensor):
            value = Tensor(jnp.asarray(value, dtype=self._value.dtype))
        out = autograd.apply_op(
            "set_value", lambda x, v: x.at[idx].set(v.astype(x.dtype)), [self, value])
        # In-place rebind: this tensor's identity now refers to the scatter
        # result, keeping the tape consistent (paddle set_value semantics).
        self._value = out._value
        self._grad_node = out._grad_node
        self._out_idx = out._out_idx
        self.stop_gradient = out.stop_gradient

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        # leading-dim slices (paddle Tensor iteration).  Without this,
        # Python's __getitem__ fallback loops forever: jnp indexing clamps
        # out-of-range instead of raising IndexError.
        if not self._value.shape:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(self._value.shape[0]))

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __repr__(self):
        prefix = "Tensor"
        try:
            val = np.array2string(self.numpy(), precision=4, separator=", ")
        except Exception:
            val = f"<traced {self._value}>"
        return (f"{prefix}(shape={self.shape}, dtype={self._value.dtype.name}, "
                f"stop_gradient={self.stop_gradient},\n       {val})")

    def __hash__(self):
        return id(self)

    # -- dunder math (fuller set patched in ops/__init__.py) ---------------
    def _binop(self, other, fn, name):
        if not isinstance(other, Tensor):
            other = Tensor(jnp.asarray(other))
        return autograd.apply_op(name, fn, [self, other])

    def __add__(self, o):
        return self._binop(o, lambda a, b: a + b, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, lambda a, b: a - b, "subtract")

    def __rsub__(self, o):
        return self._binop(o, lambda a, b: b - a, "rsubtract")

    def __mul__(self, o):
        return self._binop(o, lambda a, b: a * b, "multiply")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, lambda a, b: a / b, "divide")

    def __rtruediv__(self, o):
        return self._binop(o, lambda a, b: b / a, "rdivide")

    def __floordiv__(self, o):
        return self._binop(o, lambda a, b: a // b, "floor_divide")

    def __mod__(self, o):
        return self._binop(o, lambda a, b: a % b, "remainder")

    def __pow__(self, o):
        return self._binop(o, lambda a, b: a ** b, "pow")

    def __rpow__(self, o):
        return self._binop(o, lambda a, b: b ** a, "rpow")

    def __and__(self, o):
        return self._binop(o, jnp.bitwise_and, "bitwise_and")

    def __or__(self, o):
        return self._binop(o, jnp.bitwise_or, "bitwise_or")

    def __xor__(self, o):
        return self._binop(o, jnp.bitwise_xor, "bitwise_xor")

    def __invert__(self):
        return autograd.apply_op("bitwise_not", jnp.bitwise_not, [self])

    def __matmul__(self, o):
        return self._binop(o, lambda a, b: a @ b, "matmul")

    def __neg__(self):
        return autograd.apply_op("neg", lambda x: -x, [self])

    def __abs__(self):
        return autograd.apply_op("abs", lambda x: jnp.abs(x), [self])

    def _cmp(self, other, fn, name):
        if not isinstance(other, Tensor):
            other = Tensor(jnp.asarray(other))
        with autograd.no_grad():
            return autograd.apply_op(name, fn, [self, other])

    def __eq__(self, o):
        return self._cmp(o, lambda a, b: a == b, "equal")

    def __ne__(self, o):
        return self._cmp(o, lambda a, b: a != b, "not_equal")

    def __lt__(self, o):
        return self._cmp(o, lambda a, b: a < b, "less_than")

    def __le__(self, o):
        return self._cmp(o, lambda a, b: a <= b, "less_equal")

    def __gt__(self, o):
        return self._cmp(o, lambda a, b: a > b, "greater_than")

    def __ge__(self, o):
        return self._cmp(o, lambda a, b: a >= b, "greater_equal")

    def __invert__(self):
        with autograd.no_grad():
            return autograd.apply_op("logical_not", lambda x: ~x, [self])


class _HookHandle:
    def __init__(self, container, hook):
        self._container = container
        self._hook = hook

    def remove(self):
        try:
            self._container.remove(self._hook)
        except ValueError:
            pass


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(i._value if isinstance(i, Tensor) else i for i in idx)
    return idx


autograd._set_tensor_class(Tensor)

# jax pytree registration: a Tensor flattens to its payload, so Tensors can
# cross jit boundaries and live inside optimizer state trees.
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._value,), (t.stop_gradient, t.name)),
    lambda aux, children: Tensor(children[0], stop_gradient=aux[0], name=aux[1]),
)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor equivalent."""
    if isinstance(data, Tensor):
        value = data._value
    else:
        if isinstance(data, (list, tuple)):
            data = np.asarray(data)
        if isinstance(data, np.ndarray) and dtype is None and data.dtype == np.float64:
            data = data.astype(np.float32)
        value = jnp.asarray(data, dtype=convert_dtype(dtype))
    if dtype is not None:
        value = value.astype(convert_dtype(dtype))
    if place is not None:
        if isinstance(place, str):
            dev_type, _, idx = place.partition(":")
            place = device.Place(dev_type, int(idx or 0))
        value = jax.device_put(value, place.jax_device)
    return Tensor(value, stop_gradient=stop_gradient)
