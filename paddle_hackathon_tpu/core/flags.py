"""Global flag registry.

TPU-native equivalent of the reference's exported-gflags system
(``paddle/fluid/platform/flags.cc:36-157``, 62 ``PADDLE_DEFINE_EXPORTED_*`` flags,
exposed to Python via ``global_value_getter_setter.cc`` and
``paddle.set_flags/get_flags`` at ``python/paddle/fluid/framework.py:7125,7149``).

Here flags are a plain in-process registry seeded from ``FLAGS_*`` environment
variables at import time, mirroring the reference's env-var override behaviour.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Mapping, Union

from ..observability.sanitizers import make_rlock

_lock = make_rlock("core.flags")
_registry: Dict[str, Any] = {}
_defs: Dict[str, dict] = {}


def _coerce(value: Any, proto: Any) -> Any:
    if isinstance(proto, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(proto, int) and not isinstance(proto, bool):
        return int(value)
    if isinstance(proto, float):
        return float(value)
    return value


def define_flag(name: str, default: Any, doc: str = "") -> None:
    """Register a flag with its default; honours a FLAGS_<name> env override."""
    with _lock:
        if name in _defs:
            return
        _defs[name] = {"default": default, "doc": doc}
        env = os.environ.get("FLAGS_" + name)
        _registry[name] = _coerce(env, default) if env is not None else default


epoch = 0  # bumped on every mutation; cache keys depend on it (a traced
# op body may have read a flag value, so caches keyed pre-mutation must
# not serve post-mutation calls)


def set_flags(flags: Mapping[str, Any]) -> None:
    """paddle.set_flags equivalent (``fluid/framework.py:7125``)."""
    global epoch
    with _lock:
        for name, value in flags.items():
            if name.startswith("FLAGS_"):
                name = name[len("FLAGS_"):]
            if name not in _defs:
                raise ValueError(f"unknown flag: {name}")
            _registry[name] = _coerce(value, _defs[name]["default"])
        epoch += 1
    # mirror into the native registry so C++ components observe updates
    # (ref global_value_getter_setter.cc)
    try:
        from . import native as _native
        _native.sync_flags({k: _registry[k] for k in _registry})
    except Exception:
        pass


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    """paddle.get_flags equivalent (``fluid/framework.py:7149``)."""
    with _lock:
        if flags is None:
            return dict(_registry)
        if isinstance(flags, str):
            flags = [flags]
        out = {}
        for name in flags:
            key = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
            if key not in _registry:
                raise ValueError(f"unknown flag: {name}")
            out[name] = _registry[key]
        return out


def flag(name: str) -> Any:
    """Fast internal read of a single flag value."""
    return _registry[name]


# ---------------------------------------------------------------------------
# Core flag set (subset of the reference's flags.cc that is meaningful on TPU).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Check outputs of every op for NaN/Inf (ref: FLAGS_check_nan_inf, "
            "eager/nan_inf_utils.cc).")
define_flag("benchmark", False, "Sync after each op for timing (ref FLAGS_benchmark).")
define_flag("eager_retain_double_grad", True,
            "Retain each op's forward closure + input tensors on its grad "
            "node so paddle.grad(create_graph=True) (double grad) works "
            "out-of-the-box, like the reference's TensorWrapper retention "
            "(eager/tensor_wrapper.h). Costs peak eager-mode memory (inputs "
            "stay alive until backward releases the node); set False for "
            "memory-tight eager runs that never need higher-order grads.")
define_flag("flash_attention_min_seqlen", 1024,
            "Sequence length at which SDPA switches from the XLA softmax(QK)V "
            "composition to the Pallas flash kernel. Measured on v5e "
            "(bf16, d=64 padded to 128, fwd+bwd): since the backward kernels "
            "went bf16-MXU (pre-transposed standard contractions), flash wins "
            "at 1024 (25.2 vs 29.0 ms microbench; 97.4k vs 96.0k tok/s "
            "gpt2-small e2e), 1.4x at 2048, 2.9x at 4096, and is the only "
            "path that fits long sequences (O(S) memory vs O(S^2)). "
            "At <=512 the two paths tie (overhead-dominated).")
define_flag("use_fused_kernels", True,
            "Use Pallas fused kernels (flash attention, fused layernorm) when "
            "available; falls back to pure-XLA compositions.")
define_flag("allocator_strategy", "auto_growth",
            "Informational on TPU: XLA/PJRT owns HBM (ref FLAGS_allocator_strategy).")
define_flag("default_dtype", "float32", "Default floating dtype for new tensors.")
define_flag("jit_cache_size", 256, "Max entries in the to_static program cache.")
define_flag("matmul_precision", "highest",
            "XLA dot/conv precision for float32 operands: 'highest' = true f32 "
            "accumulate (6-pass bf16 on the MXU), 'high' = TF32-like 3-pass, "
            "'default' = fastest 1-pass bf16. bf16 tensors always take the "
            "native MXU path. Analog of the reference's TF32 switch "
            "(paddle/fluid/platform/device/gpu/cuda/cuda_device_function.h).")
define_flag("log_level", 0, "VLOG-style verbosity for the framework itself.")
