"""Device / place management.

TPU-native equivalent of the reference's Place + DeviceContext machinery
(``paddle/phi/common/place.h:27``, ``paddle/phi/core/device_context.h:34``,
``python/paddle/device/__init__.py:294`` ``set_device``). PJRT (through JAX) owns
the actual device runtime, streams and the HBM allocator, so a Place here is a
thin handle onto a ``jax.Device`` plus helpers for host<->device transfer and
memory stats (ref ``paddle/fluid/memory/stats.h:112``).
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

_state = threading.local()


class Place:
    """A device handle. ``Place('tpu', 0)``, ``Place('cpu')``.

    Mirrors ``phi::Place`` (``paddle/phi/common/place.h:27``) — equality is
    (device_type, device_id).
    """

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    @property
    def jax_device(self) -> jax.Device:
        devs = _devices_of_type(self.device_type)
        if not devs:
            raise RuntimeError(f"no {self.device_type!r} devices visible to JAX")
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type in ("tpu", "axon")


def _devices_of_type(device_type: str):
    if device_type in ("tpu", "axon"):
        # The axon tunnel exposes the real chip under platform name 'axon'.
        for plat in ("tpu", "axon"):
            try:
                devs = jax.devices(plat)
                if devs:
                    return devs
            except RuntimeError:
                continue
        return []
    try:
        return jax.devices(device_type)
    except RuntimeError:
        return []


def _default_place() -> Place:
    for t in ("tpu", "gpu", "cpu"):
        if _devices_of_type(t):
            return Place(t, 0)
    return Place("cpu", 0)


def set_device(device: str) -> Place:
    """paddle.device.set_device equivalent (``device/__init__.py:294``).

    Accepts 'tpu', 'tpu:1', 'cpu', ...
    """
    if ":" in device:
        dev_type, idx = device.split(":", 1)
        place = Place(dev_type, int(idx))
    else:
        place = Place(device, 0)
    place.jax_device  # validate eagerly
    _state.place = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def current_place() -> Place:
    place = getattr(_state, "place", None)
    if place is None:
        place = _default_place()
        _state.place = place
    return place


def is_compiled_with_tpu() -> bool:
    return bool(_devices_of_type("tpu"))


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        device_type = current_place().device_type
    return len(_devices_of_type(device_type))


def synchronize(place: Optional[Place] = None) -> None:
    """Block until all outstanding work on the device is complete.

    Equivalent of ``paddle.device.cuda.synchronize`` — on PJRT we issue a tiny
    computation and block on it, which orders after previously enqueued work.
    """
    import jax.numpy as jnp

    dev = (place or current_place()).jax_device
    jax.device_put(jnp.zeros((), jnp.int32), dev).block_until_ready()


def memory_stats(place: Optional[Place] = None) -> dict:
    """Device memory statistics (ref ``memory/stats.h:112`` DEVICE_MEMORY_STAT_*).

    Backed by PJRT's per-device memory_stats when the platform reports them.
    """
    dev = (place or current_place()).jax_device
    try:
        stats = dev.memory_stats() or {}
    except Exception:  # platform without stats (CPU)
        stats = {}
    return {
        "allocated.current": stats.get("bytes_in_use", 0),
        "allocated.peak": stats.get("peak_bytes_in_use", 0),
        "reserved.total": stats.get("bytes_limit", 0),
        "num_allocs": stats.get("num_allocs", 0),
    }


def max_memory_allocated(place: Optional[Place] = None) -> int:
    return memory_stats(place)["allocated.peak"]


def memory_allocated(place: Optional[Place] = None) -> int:
    return memory_stats(place)["allocated.current"]


# -- capability probes + vendor Places (ref python/paddle/device/__init__.py)
# On this framework every accelerator place is the TPU chip; the CUDA/ROCm/
# NPU/MLU/XPU/IPU probes answer False so device-branching user code takes
# its generic path.
def get_cudnn_version():
    return None


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    # XLA plays CINN's role (compiler backend) but the flag answers the
    # reference's question "is the CINN backend present" -> False
    return False


def XPUPlace(dev_id=0):
    return Place("tpu", dev_id)


def IPUPlace(dev_id=0):
    return Place("tpu", dev_id)


def MLUPlace(dev_id=0):
    return Place("tpu", dev_id)


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu", "tpu")]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device()
            if d.split(":")[0] not in ("cpu", "gpu", "tpu")]
