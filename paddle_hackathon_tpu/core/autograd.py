"""Eager reverse-mode autograd engine.

TPU-native equivalent of the reference's eager autograd
(``paddle/fluid/eager/``): ``GradNode`` mirrors ``egr::GradNodeBase``
(``eager/grad_node_info.h:168``), gradient accumulation mirrors
``GradTensorHolder`` (``eager/grad_tensor_holder.cc``), and the engine is the
same ready-queue / in-degree-counting walk as ``egr::RunBackward``
(``eager/backward.cc:556``).

The key architectural difference from the reference: instead of a hand-written
grad kernel per op (generated from ``legacy_backward.yaml``), every op's VJP is
obtained from ``jax.vjp`` at forward time — XLA is the single lowering path, so
the "backward kernel" is just the transposed jaxpr, fused by XLA like any other
computation. Saved tensors (the reference's ``TensorWrapper``,
``eager/tensor_wrapper.h``) are the vjp residuals captured in the closure.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import flags

_tls = threading.local()

# Injected by tensor.py at import time to avoid a circular import.
Tensor = None  # type: ignore
_amp_mod = None  # lazily bound amp module (AMP cast hook)
# Injected by static/program.py at import time: static-graph recording hook.
_static_module = None
# Set by profiler while recording: name -> context-manager factory.
_profiler_hook = None


def _set_tensor_class(cls) -> None:
    global Tensor
    Tensor = cls


# ---------------------------------------------------------------------------
# Grad mode
# ---------------------------------------------------------------------------

def is_grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad equivalent — suspends tape recording."""
    prev = is_grad_enabled()
    _tls.grad_enabled = False
    try:
        yield
    finally:
        _tls.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = is_grad_enabled()
    _tls.grad_enabled = True
    try:
        yield
    finally:
        _tls.grad_enabled = prev


def set_grad_enabled(mode: bool):
    @contextlib.contextmanager
    def _ctx():
        prev = is_grad_enabled()
        _tls.grad_enabled = bool(mode)
        try:
            yield
        finally:
            _tls.grad_enabled = prev

    return _ctx()


# ---------------------------------------------------------------------------
# Graph nodes
# ---------------------------------------------------------------------------

class _LeafSlot:
    """Accumulation target for a leaf tensor (ref GradNodeAccumulation,
    ``eager/accumulation/accumulation_node.h``)."""

    __slots__ = ("tensor",)

    def __init__(self, tensor):
        self.tensor = tensor


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn`` maps the op's output cotangents (a tuple, one entry per output)
    to input cotangents (a tuple, one per differentiable input).
    ``parents[i]`` is either ``(GradNode, out_idx)`` for a non-leaf input or a
    ``_LeafSlot`` for a leaf input.
    """

    __slots__ = ("name", "vjp_fn", "parents", "n_outputs", "out_avals",
                 "hooks", "_buffer", "_arrived", "_expected", "__weakref__")

    def __init__(self, name: str, vjp_fn: Callable, parents: list,
                 n_outputs: int, out_avals: list):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = parents
        self.n_outputs = n_outputs
        self.out_avals = out_avals  # (shape, dtype) per output, for zero-fill
        self.hooks: Optional[dict] = None  # out_idx -> [hook fns]
        self._buffer: Optional[list] = None
        self._arrived = 0
        self._expected = 0

    def release(self) -> None:
        """Drop saved residuals (retain_graph=False semantics)."""
        self.vjp_fn = None
        self.parents = []


# ---------------------------------------------------------------------------
# Engine — ready-queue over the GradNode DAG (ref egr::RunBackward,
# eager/backward.cc:556: in-degree counting + queue).
# ---------------------------------------------------------------------------

def run_backward(tensors: Sequence, grad_tensors: Sequence, retain_graph: bool = False):
    roots: List[Tuple[GradNode, int, Any]] = []
    for t, g in zip(tensors, grad_tensors):
        if t._grad_node is None:
            # Backward on a leaf: its grad is just the incoming cotangent.
            _accumulate_leaf(t, g)
            continue
        roots.append((t._grad_node, t._out_idx, g))
    if not roots:
        return

    # Pass 1: count, for every reachable node, how many cotangent deliveries it
    # will receive (edges from consumer nodes reachable from the roots).
    expected = {}
    visited = set()
    stack = [n for n, _, _ in roots]
    for n, _, _ in roots:
        expected[n] = expected.get(n, 0)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for parent in node.parents:
            if isinstance(parent, _LeafSlot):
                continue
            pnode, _ = parent
            expected[pnode] = expected.get(pnode, 0) + 1
            if id(pnode) not in visited:
                stack.append(pnode)

    for n, _, g in roots:
        expected[n] = expected.get(n, 0) + 1

    # Pass 2: ready queue.
    queue: deque = deque()

    def deliver(node: GradNode, out_idx: int, grad) -> None:
        if node._buffer is None:
            node._buffer = [None] * node.n_outputs
            node._arrived = 0
            node._expected = expected[node]
        buf = node._buffer
        # Cast cotangent to the producing op's output dtype — the AMP boundary
        # transform (a blacklisted f32 op may feed back into a bf16 producer;
        # ref fluid data_type_transform.cc on the grad path).
        out_dtype = node.out_avals[out_idx][1]
        if grad.dtype != out_dtype:
            grad = grad.astype(out_dtype)
        buf[out_idx] = grad if buf[out_idx] is None else buf[out_idx] + grad
        node._arrived += 1
        if node._arrived == node._expected:
            queue.append(node)

    for n, idx, g in roots:
        deliver(n, idx, g)

    while queue:
        node = queue.popleft()
        cotangents = tuple(
            buf if buf is not None else jnp.zeros(shape, dtype)
            for buf, (shape, dtype) in zip(node._buffer, node.out_avals)
        )
        if node.hooks:
            cotangents = list(cotangents)
            for out_idx, hook_fns in node.hooks.items():
                for hook in hook_fns:
                    res = hook(_wrap_hook_arg(cotangents[out_idx]))
                    if res is not None:
                        cotangents[out_idx] = (
                            res._value if isinstance(res, Tensor) else res)
            cotangents = tuple(cotangents)
        node._buffer = None
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad node {node.name} has been released; call backward with "
                "retain_graph=True to backprop through the graph twice")
        in_grads = node.vjp_fn(cotangents)
        parents = node.parents
        if not retain_graph:
            node.release()
        for parent, grad in zip(parents, in_grads):
            if isinstance(parent, _LeafSlot):
                _accumulate_leaf(parent.tensor, grad)
            else:
                pnode, out_idx = parent
                deliver(pnode, out_idx, grad)


def _accumulate_leaf(tensor, grad) -> None:
    for hook in tensor._grad_hooks:
        out = hook(_wrap_hook_arg(grad))
        if out is not None:
            grad = out._value if isinstance(out, Tensor) else out
    if tensor.stop_gradient:
        return
    if tensor._grad_value is None:
        tensor._grad_value = grad
    else:
        tensor._grad_value = tensor._grad_value + grad


def _wrap_hook_arg(grad):
    t = Tensor(grad, stop_gradient=True)
    return t


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad equivalent (ref ``egr::GeneralGrad``, eager/backward.cc:38).

    Computes gradients of ``outputs`` w.r.t. ``inputs`` without touching
    ``.grad`` of other leaves. ``create_graph`` (double grad) is not supported
    by the eager tape; use the jit path (jax.grad composition) for higher-order
    derivatives.
    """
    if create_graph:
        raise NotImplementedError(
            "create_graph=True is not supported on the eager tape; wrap the "
            "computation in paddle_hackathon_tpu.jit.to_static and compose "
            "jax.grad for higher-order derivatives")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    grad_outputs = [jnp.ones(o.shape, o.dtype) if g is None else g._value
                    for o, g in zip(outputs, grad_outputs)]

    # Non-leaf (intermediate) inputs: capture their accumulated cotangent via
    # a temporary node hook (the engine applies hooks when the producing node
    # becomes ready) — mirrors GeneralGrad's input-node capture.
    captures = {}
    temp_hooks = []
    for inp in inputs:
        if inp._grad_node is not None:
            def _capture(g, _key=id(inp)):
                captures[_key] = g._value
            temp_hooks.append(inp.register_hook(_capture))

    # Temporarily swap leaf accumulation: stash and restore .grad of leaves that
    # are not requested, capture grads of requested inputs.
    saved = [(t, t._grad_value) for t in _all_leaves(outputs)]
    for t, _ in saved:
        t._grad_value = None
    try:
        run_backward(outputs, grad_outputs,
                     retain_graph=bool(retain_graph))
        results = []
        for inp in inputs:
            if inp._grad_node is not None:
                g = captures.get(id(inp))
            else:
                g = inp._grad_value
            if g is None and not allow_unused:
                raise ValueError(
                    "one of the input tensors receives no gradient; pass "
                    "allow_unused=True to return None for it")
            results.append(None if g is None else Tensor(g, stop_gradient=True))
        return results
    finally:
        for t, old in saved:
            t._grad_value = old
        for h in temp_hooks:
            h.remove()


def _all_leaves(outputs):
    leaves = []
    seen = set()
    stack = [t._grad_node for t in outputs if t._grad_node is not None]
    visited = set()
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for parent in node.parents:
            if isinstance(parent, _LeafSlot):
                if id(parent.tensor) not in seen:
                    seen.add(id(parent.tensor))
                    leaves.append(parent.tensor)
            else:
                stack.append(parent[0])
    return leaves


# ---------------------------------------------------------------------------
# Op application — the single entry every framework op goes through.
# Equivalent of the generated ``*_final_state_dygraph_function`` bodies
# (eager_gen.py): forward compute + conditional GradNode construction.
# ---------------------------------------------------------------------------

def _check_nan_inf(name, vals):
    for v in vals:
        if isinstance(v, jax.Array) and jnp.issubdtype(v.dtype, jnp.floating):
            if bool(jnp.any(~jnp.isfinite(v))):
                raise FloatingPointError(
                    f"NaN or Inf detected in output of op {name!r} "
                    "(FLAGS_check_nan_inf; ref eager/nan_inf_utils.cc)")


def apply_op(name: str, fn: Callable, args: Sequence[Any], n_outputs: int = 1):
    """Run ``fn(*jax_args)`` and record a GradNode if any input needs grad.

    ``args`` may mix Tensors, jax arrays, python scalars and None. Tensors with
    ``stop_gradient=False`` and floating dtype become vjp-differentiable inputs;
    everything else is closed over as a constant.

    In static-graph mode, ops touching a symbolic Variable append an
    instruction to the current Program instead of executing (ref the
    append_op path of ``fluid/framework.py``).
    """
    sm = _static_module
    if (sm is not None and sm.in_static_mode()
            and any(isinstance(a, sm.Variable) for a in args)):
        return sm.default_main_program().record_op(name, fn, args, n_outputs)
    hook = _profiler_hook
    if hook is not None:
        with hook(name):
            return _apply_op_impl(name, fn, args, n_outputs)
    return _apply_op_impl(name, fn, args, n_outputs)


def _apply_op_impl(name: str, fn: Callable, args: Sequence[Any], n_outputs: int = 1):
    jax_args = []
    diff_positions = []
    tape_on = is_grad_enabled()
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            v = a._value
            jax_args.append(v)
            if tape_on and not a.stop_gradient and jnp.issubdtype(
                    jnp.result_type(v), jnp.inexact):
                diff_positions.append(i)
        else:
            jax_args.append(a)

    # AMP auto-cast preamble (ref eager_gen.py:363 generated AMP logic).
    global _amp_mod
    if _amp_mod is None:
        from .. import amp as _amp_mod_  # late import: amp depends on tensor
        _amp_mod = _amp_mod_
    if _amp_mod._amp_state() is not None:
        jax_args = _amp_mod.cast_inputs_for_op(name, jax_args)

    if not diff_positions:
        out = fn(*jax_args)
        return _wrap_outputs(name, out, n_outputs, node=None)

    const_args = list(jax_args)

    def closed(*diff_vals):
        call = list(const_args)
        for pos, val in zip(diff_positions, diff_vals):
            call[pos] = val
        return fn(*call)

    diff_vals = [jax_args[i] for i in diff_positions]
    out, vjp_fn = jax.vjp(closed, *diff_vals)

    parents = []
    for pos in diff_positions:
        src = args[pos]
        if src._grad_node is not None:
            parents.append((src._grad_node, src._out_idx))
        else:
            parents.append(_LeafSlot(src))

    outs = out if isinstance(out, tuple) else (out,)
    out_avals = [(o.shape, o.dtype) for o in outs]

    def node_vjp(cotangents, _vjp=vjp_fn, _single=not isinstance(out, tuple)):
        with no_grad():
            return _vjp(cotangents[0] if _single else cotangents)

    node = GradNode(name, node_vjp, parents, len(outs), out_avals)
    return _wrap_outputs(name, out, n_outputs, node=node)


def _wrap_outputs(name, out, n_outputs, node):
    if flags.flag("check_nan_inf"):
        _check_nan_inf(name, out if isinstance(out, tuple) else (out,))
    stop = node is None
    if isinstance(out, tuple):
        return tuple(
            Tensor(o, stop_gradient=stop, _grad_node=node, _out_idx=i)
            for i, o in enumerate(out))
    return Tensor(out, stop_gradient=stop, _grad_node=node, _out_idx=0)


def primitive(name: str):
    """Decorator turning a pure jax function into a taped framework op.

    The wrapped function receives jax values; the public wrapper accepts
    Tensors / scalars. Keyword arguments are static (non-differentiable) and
    folded into the closure.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            call = functools.partial(fn, **kwargs) if kwargs else fn
            return apply_op(name, call, args)

        wrapper.__framework_op__ = name
        return wrapper

    return deco
