"""Eager reverse-mode autograd engine.

TPU-native equivalent of the reference's eager autograd
(``paddle/fluid/eager/``): ``GradNode`` mirrors ``egr::GradNodeBase``
(``eager/grad_node_info.h:168``), gradient accumulation mirrors
``GradTensorHolder`` (``eager/grad_tensor_holder.cc``), and the engine is the
same ready-queue / in-degree-counting walk as ``egr::RunBackward``
(``eager/backward.cc:556``).

The key architectural difference from the reference: instead of a hand-written
grad kernel per op (generated from ``legacy_backward.yaml``), every op's VJP is
obtained from ``jax.vjp`` at forward time — XLA is the single lowering path, so
the "backward kernel" is just the transposed jaxpr, fused by XLA like any other
computation. Saved tensors (the reference's ``TensorWrapper``,
``eager/tensor_wrapper.h``) are the vjp residuals captured in the closure.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import types
from collections import deque
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import flags

_tls = threading.local()

# Injected by tensor.py at import time to avoid a circular import.
Tensor = None  # type: ignore
_amp_mod = None  # lazily bound amp module (AMP cast hook)
# Injected by static/program.py at import time: static-graph recording hook.
_static_module = None
# Set by profiler while recording: name -> context-manager factory.
_profiler_hook = None


def _set_tensor_class(cls) -> None:
    global Tensor
    Tensor = cls


# ---------------------------------------------------------------------------
# Grad mode
# ---------------------------------------------------------------------------

def is_grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad equivalent — suspends tape recording."""
    prev = is_grad_enabled()
    _tls.grad_enabled = False
    try:
        yield
    finally:
        _tls.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = is_grad_enabled()
    _tls.grad_enabled = True
    try:
        yield
    finally:
        _tls.grad_enabled = prev


def set_grad_enabled(mode: bool):
    @contextlib.contextmanager
    def _ctx():
        prev = is_grad_enabled()
        _tls.grad_enabled = bool(mode)
        try:
            yield
        finally:
            _tls.grad_enabled = prev

    return _ctx()


# ---------------------------------------------------------------------------
# Graph nodes
# ---------------------------------------------------------------------------

class _LeafSlot:
    """Accumulation target for a leaf tensor (ref GradNodeAccumulation,
    ``eager/accumulation/accumulation_node.h``)."""

    __slots__ = ("tensor",)

    def __init__(self, tensor):
        self.tensor = tensor


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn`` maps the op's output cotangents (a tuple, one entry per output)
    to input cotangents (a tuple, one per differentiable input).
    ``parents[i]`` is either ``(GradNode, out_idx)`` for a non-leaf input or a
    ``_LeafSlot`` for a leaf input.
    """

    __slots__ = ("name", "vjp_fn", "parents", "n_outputs", "out_avals",
                 "hooks", "fwd_fn", "in_tensors", "_buffer", "_arrived",
                 "_expected", "__weakref__")

    def __init__(self, name: str, vjp_fn: Callable, parents: list,
                 n_outputs: int, out_avals: list):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = parents
        self.n_outputs = n_outputs
        self.out_avals = out_avals  # (shape, dtype) per output, for zero-fill
        self.hooks: Optional[dict] = None  # out_idx -> [hook fns]
        # For create_graph=True (double grad): the op's closed forward fn and
        # its differentiable input Tensors, so the backward can be re-derived
        # as a *taped* computation (the reference keeps the same data as
        # TensorWrappers on the grad node; eager/tensor_wrapper.h).
        self.fwd_fn: Optional[Callable] = None
        self.in_tensors: Optional[tuple] = None
        self._buffer: Optional[list] = None
        self._arrived = 0
        self._expected = 0

    def release(self) -> None:
        """Drop saved residuals (retain_graph=False semantics)."""
        self.vjp_fn = None
        self.parents = []
        self.fwd_fn = None
        self.in_tensors = None


# ---------------------------------------------------------------------------
# Engine — ready-queue over the GradNode DAG (ref egr::RunBackward,
# eager/backward.cc:556: in-degree counting + queue).
# ---------------------------------------------------------------------------

def _count_expected(roots):
    """Pass 1: for every node reachable from the roots, count how many
    cotangent deliveries it will receive (one per consumer edge, plus one
    per root entry)."""
    expected = {}
    visited = set()
    stack = [n for n, _, _ in roots]
    for n, _, _ in roots:
        expected[n] = expected.get(n, 0)
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for parent in node.parents:
            if isinstance(parent, _LeafSlot):
                continue
            pnode, _ = parent
            expected[pnode] = expected.get(pnode, 0) + 1
            if id(pnode) not in visited:
                stack.append(pnode)
    for n, _, _ in roots:
        expected[n] = expected.get(n, 0) + 1
    return expected


def _engine_walk(roots, *, zero_fill, run_hook, apply_node, on_leaf,
                 after_node=None):
    """Pass 2: the shared ready-queue walk (ref egr::RunBackward).

    Cotangent values are opaque to the walk — raw jax arrays in the plain
    engine, taped Tensors in the create_graph engine; both support
    ``.dtype`` / ``.astype`` / ``+``. The four callbacks supply the
    mode-specific behavior.
    """
    expected = _count_expected(roots)
    queue: deque = deque()

    def deliver(node: GradNode, out_idx: int, grad) -> None:
        if node._buffer is None:
            node._buffer = [None] * node.n_outputs
            node._arrived = 0
            node._expected = expected[node]
        buf = node._buffer
        # Cast cotangent to the producing op's output dtype — the AMP boundary
        # transform (a blacklisted f32 op may feed back into a bf16 producer;
        # ref fluid data_type_transform.cc on the grad path).
        out_dtype = node.out_avals[out_idx][1]
        if grad.dtype != out_dtype:
            grad = grad.astype(out_dtype)
        buf[out_idx] = grad if buf[out_idx] is None else buf[out_idx] + grad
        node._arrived += 1
        if node._arrived == node._expected:
            queue.append(node)

    for n, idx, g in roots:
        deliver(n, idx, g)

    while queue:
        node = queue.popleft()
        cotangents = [
            buf if buf is not None else zero_fill(shape, dtype)
            for buf, (shape, dtype) in zip(node._buffer, node.out_avals)
        ]
        if node.hooks:
            for out_idx, hook_fns in node.hooks.items():
                for hook in hook_fns:
                    res = run_hook(hook, cotangents[out_idx])
                    if res is not None:
                        cotangents[out_idx] = res
        node._buffer = None
        in_grads = apply_node(node, tuple(cotangents))
        parents = node.parents
        if after_node is not None:
            after_node(node)
        for parent, grad in zip(parents, in_grads):
            if isinstance(parent, _LeafSlot):
                on_leaf(parent.tensor, grad)
            else:
                pnode, out_idx = parent
                deliver(pnode, out_idx, grad)


def run_backward(tensors: Sequence, grad_tensors: Sequence, retain_graph: bool = False):
    roots: List[Tuple[GradNode, int, Any]] = []
    for t, g in zip(tensors, grad_tensors):
        if t._grad_node is None:
            # Backward on a leaf: its grad is just the incoming cotangent.
            _accumulate_leaf(t, g)
            continue
        roots.append((t._grad_node, t._out_idx, g))
    if not roots:
        return

    def run_hook(hook, cot):
        res = hook(_wrap_hook_arg(cot))
        if res is None:
            return None
        return res._value if isinstance(res, Tensor) else res

    def apply_node(node, cotangents):
        if node.vjp_fn is None:
            raise RuntimeError(
                f"grad node {node.name} has been released; call backward with "
                "retain_graph=True to backprop through the graph twice")
        return node.vjp_fn(cotangents)

    _engine_walk(
        roots,
        zero_fill=jnp.zeros,
        run_hook=run_hook,
        apply_node=apply_node,
        on_leaf=_accumulate_leaf,
        after_node=None if retain_graph else GradNode.release,
    )


def _accumulate_leaf(tensor, grad) -> None:
    for hook in tensor._grad_hooks:
        out = hook(_wrap_hook_arg(grad))
        if out is not None:
            grad = out._value if isinstance(out, Tensor) else out
    if tensor.stop_gradient:
        return
    if tensor._grad_value is None:
        tensor._grad_value = grad
    else:
        tensor._grad_value = tensor._grad_value + grad


def _wrap_hook_arg(grad):
    t = Tensor(grad, stop_gradient=True)
    return t


def _run_backward_taped(roots, leaf_grads):
    """create_graph=True engine: the same ready-queue walk as
    :func:`run_backward`, but cotangents are *Tensors* and every node's
    backward is re-applied through :func:`apply_op` — so the produced grads
    carry their own GradNodes and are differentiable again (ref
    ``egr::RunBackward`` with ``create_graph``; double-grad nodes from
    eager_gen).  Second-order paths through saved inputs are correct because
    each node's backward recomputes its forward inside ``jax.vjp`` from the
    retained input Tensors.

    ``roots`` is [(node, out_idx, cot_tensor)]; ``leaf_grads`` is a dict
    {id(leaf_tensor): Tensor} filled with accumulated (taped) leaf grads.
    """

    def zero_fill(shape, dtype):
        return Tensor(jnp.zeros(shape, dtype), stop_gradient=True)

    def run_hook(hook, cot):
        res = hook(cot)
        if res is None:
            return None
        return res if isinstance(res, Tensor) else Tensor(res,
                                                          stop_gradient=True)

    def apply_node(node, cotangents):
        if node.fwd_fn is None:
            raise RuntimeError(
                f"grad node {node.name} cannot be differentiated again "
                "(released, produced by an op that does not retain its "
                "forward — e.g. a PyLayer — or recorded with "
                "FLAGS_eager_retain_double_grad off); create_graph=True "
                "needs the taped forward")
        n_in = len(node.in_tensors)
        single_out = node.n_outputs == 1

        def bwd(*vals, _fwd=node.fwd_fn, _n=n_in, _single=single_out):
            xs, cts = vals[:_n], vals[_n:]
            _, vjp_fn = jax.vjp(_fwd, *xs)
            grads = vjp_fn(cts[0] if _single else tuple(cts))
            return grads if len(grads) > 1 else grads[0]

        in_grads = apply_op(node.name + "_grad", bwd,
                            [*node.in_tensors, *cotangents],
                            n_outputs=n_in)
        return in_grads if isinstance(in_grads, tuple) else (in_grads,)

    def on_leaf(tensor, grad_t):
        for hook in tensor._grad_hooks:
            out = hook(grad_t)
            if out is not None:
                grad_t = out
        if tensor.stop_gradient:
            return
        key = id(tensor)
        prev = leaf_grads.get(key)
        leaf_grads[key] = grad_t if prev is None else prev + grad_t

    _engine_walk(roots, zero_fill=zero_fill, run_hook=run_hook,
                 apply_node=apply_node, on_leaf=on_leaf)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """paddle.grad equivalent (ref ``egr::GeneralGrad``, eager/backward.cc:38).

    Computes gradients of ``outputs`` w.r.t. ``inputs`` without touching
    ``.grad`` of other leaves. With ``create_graph=True`` the returned grads
    are themselves taped (double grad): each grad node's backward is re-run
    through the tape, recomputing its forward inside ``jax.vjp`` so
    second-order terms through saved inputs are included.
    """
    if create_graph:
        outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if grad_outputs is None:
            grad_outputs = [None] * len(outputs)
        root_cots = [
            Tensor(jnp.ones(o.shape, o.dtype), stop_gradient=True)
            if g is None else (g if isinstance(g, Tensor) else Tensor(g))
            for o, g in zip(outputs, grad_outputs)]
        leaf_grads: dict = {}
        roots = []
        # Intermediate (non-leaf) requested inputs: capture their accumulated
        # cotangent Tensor via a temporary hook.
        captures: dict = {}
        temp_hooks = []
        for inp in inputs:
            if inp._grad_node is not None:
                def _capture(g, _key=id(inp)):
                    captures[_key] = g
                temp_hooks.append(inp.register_hook(_capture))
        try:
            for t, g in zip(outputs, root_cots):
                if t._grad_node is None:
                    leaf_grads[id(t)] = g
                else:
                    roots.append((t._grad_node, t._out_idx, g))
            if roots:
                _run_backward_taped(roots, leaf_grads)
            results = []
            for inp in inputs:
                if inp._grad_node is not None:
                    g = captures.get(id(inp))
                else:
                    g = leaf_grads.get(id(inp))
                if g is None and not allow_unused:
                    raise ValueError(
                        "one of the input tensors receives no gradient; pass "
                        "allow_unused=True to return None for it")
                results.append(g)
            return results
        finally:
            for h in temp_hooks:
                h.remove()
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    grad_outputs = [jnp.ones(o.shape, o.dtype) if g is None else g._value
                    for o, g in zip(outputs, grad_outputs)]

    # Non-leaf (intermediate) inputs: capture their accumulated cotangent via
    # a temporary node hook (the engine applies hooks when the producing node
    # becomes ready) — mirrors GeneralGrad's input-node capture.
    captures = {}
    temp_hooks = []
    for inp in inputs:
        if inp._grad_node is not None:
            def _capture(g, _key=id(inp)):
                captures[_key] = g._value
            temp_hooks.append(inp.register_hook(_capture))

    # Temporarily swap leaf accumulation: stash and restore .grad of leaves that
    # are not requested, capture grads of requested inputs.
    # Stash .grad of every leaf the walk can touch — including *leaf outputs*
    # (run_backward accumulates their cotangent straight into ._grad_value;
    # without stashing, repeated grad() calls double-count and pollute .grad).
    stash_leaves = _all_leaves(outputs)
    seen_ids = {id(t) for t in stash_leaves}
    for t in outputs:
        if t._grad_node is None and id(t) not in seen_ids:
            seen_ids.add(id(t))
            stash_leaves.append(t)
    saved = [(t, t._grad_value) for t in stash_leaves]
    for t, _ in saved:
        t._grad_value = None
    try:
        run_backward(outputs, grad_outputs,
                     retain_graph=bool(retain_graph))
        results = []
        for inp in inputs:
            if inp._grad_node is not None:
                g = captures.get(id(inp))
            else:
                g = inp._grad_value
            if g is None and not allow_unused:
                raise ValueError(
                    "one of the input tensors receives no gradient; pass "
                    "allow_unused=True to return None for it")
            results.append(None if g is None else Tensor(g, stop_gradient=True))
        return results
    finally:
        for t, old in saved:
            t._grad_value = old
        for h in temp_hooks:
            h.remove()


def _all_leaves(outputs):
    leaves = []
    seen = set()
    stack = [t._grad_node for t in outputs if t._grad_node is not None]
    visited = set()
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for parent in node.parents:
            if isinstance(parent, _LeafSlot):
                if id(parent.tensor) not in seen:
                    seen.add(id(parent.tensor))
                    leaves.append(parent.tensor)
            else:
                stack.append(parent[0])
    return leaves


# ---------------------------------------------------------------------------
# Op application — the single entry every framework op goes through.
# Equivalent of the generated ``*_final_state_dygraph_function`` bodies
# (eager_gen.py): forward compute + conditional GradNode construction.
# ---------------------------------------------------------------------------

def _check_nan_inf(name, vals):
    for v in vals:
        if isinstance(v, jax.Array) and jnp.issubdtype(v.dtype, jnp.floating):
            if bool(jnp.any(~jnp.isfinite(v))):
                raise FloatingPointError(
                    f"NaN or Inf detected in output of op {name!r} "
                    "(FLAGS_check_nan_inf; ref eager/nan_inf_utils.cc)")


def apply_op(name: str, fn: Callable, args: Sequence[Any], n_outputs: int = 1):
    """Run ``fn(*jax_args)`` and record a GradNode if any input needs grad.

    ``args`` may mix Tensors, jax arrays, python scalars and None. Tensors with
    ``stop_gradient=False`` and floating dtype become vjp-differentiable inputs;
    everything else is closed over as a constant.

    In static-graph mode, ops touching a symbolic Variable append an
    instruction to the current Program instead of executing (ref the
    append_op path of ``fluid/framework.py``).
    """
    sm = _static_module
    if (sm is not None and sm.in_static_mode()
            and any(isinstance(a, sm.Variable) for a in args)):
        return sm.default_main_program().record_op(name, fn, args, n_outputs)
    hook = _profiler_hook
    if hook is not None:
        with hook(name):
            return _apply_op_impl(name, fn, args, n_outputs)
    return _apply_op_impl(name, fn, args, n_outputs)


# ---------------------------------------------------------------------------
# Eager dispatch cache.  The reference's eager loop runs generated C++ op
# functions; here every taped op used to re-trace ``jax.vjp`` on each call
# (~1.7 ms/op on CPU vs 0.15 ms untaped — VERDICT round-1 weak #6).  For
# cacheable ops (module-level fn or partial-with-hashable-kwargs, hashable
# non-tensor args) the forward runs through a cached ``jax.jit`` and the
# backward through a cached jit that *recomputes* the forward inside
# ``jax.vjp`` — compile once per (op, signature), dispatch fast after,
# and no residuals are held alive (backward rematerializes).
# ---------------------------------------------------------------------------

_dispatch_cache: dict = {}
_DISPATCH_CACHE_MAX = 4096
_dispatch_epoch = -1  # flags.epoch the cache was built under
# churn defense: an op whose key keeps varying (e.g. a per-step python
# float static) would compile on every call — after this many distinct
# builds for one code object it is blacklisted back to the retrace path
_dispatch_builds: dict = {}
_dispatch_blacklist: set = set()
_DISPATCH_CHURN_LIMIT = 32


def _dispatch_cache_fresh():
    """The cache is valid for one flags epoch: a traced op body may have
    read a flag, so any mutation invalidates everything (stale entries
    could never hit again anyway — clearing also stops them pinning dead
    executables and eating the size cap)."""
    global _dispatch_epoch
    if _dispatch_epoch != flags.epoch:
        _dispatch_cache.clear()
        _dispatch_builds.clear()
        _dispatch_blacklist.clear()
        _dispatch_epoch = flags.epoch
    return _dispatch_cache


def _hashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


class _Unfreezable:
    pass


# identity-hashed types whose identity IS stable across calls (module-level
# functions, modules, classes, numpy ufuncs) — everything else that falls
# back to object.__hash__ is a mutable per-instance object (Tensor, Layer,
# client handles): keying on those churns the cache toward the blacklist,
# and a cached jit that traced such an object's state would serve stale
# results after in-place mutation (ADVICE r2)
_STABLE_IDENTITY_TYPES = (types.FunctionType, types.BuiltinFunctionType,
                          types.ModuleType, type, np.ufunc)


def _freeze(x):
    """(key_form, call_form) for a static value, or _Unfreezable.

    call_form is what the cached jit receives (lists become tuples — jnp
    APIs accept either); key_form additionally carries the TYPE of every
    scalar so ==-equal values of different types (0 vs 0.0 vs False) never
    share an entry (they trace to different dtypes).  Rejected outright:
    NaN floats (never ==-equal: every call would insert a fresh
    never-hittable key) and locally-defined callables (fresh object per
    call, keyed by identity: every call would compile a new executable)."""
    if isinstance(x, (list, tuple)):
        kids = [_freeze(v) for v in x]
        if any(k is _Unfreezable for k in kids):
            return _Unfreezable
        return ((type(x).__name__,) + tuple(k for k, _ in kids),
                tuple(c for _, c in kids))
    if isinstance(x, float) and x != x:
        return _Unfreezable
    if callable(x) and "<locals>" in getattr(x, "__qualname__", ""):
        return _Unfreezable
    if (type(x).__hash__ is object.__hash__
            and not isinstance(x, _STABLE_IDENTITY_TYPES)):
        return _Unfreezable
    if not _hashable(x):
        return _Unfreezable
    return ((type(x), x), x)


def _dispatch_key(fn, jax_args, diff_positions):
    base = fn.func if isinstance(fn, functools.partial) else fn
    cells = ()
    if getattr(base, "__closure__", None):
        # per-call closures are the dominant op pattern (the body captures
        # static flags like transpose_x) — key on the stable code object
        # plus the captured values; any unhashable capture (arrays, rng
        # keys, layers with state) disqualifies the op
        try:
            frozen = [_freeze(c.cell_contents) for c in base.__closure__]
        except ValueError:  # empty cell
            return None
        if any(c is _Unfreezable for c in frozen):
            return None
        cells = tuple(k for k, _ in frozen)
    # identity = the code object: per-call lambdas/closures (fresh function
    # objects every dispatch) still share one cache entry per definition
    # site, and the cache never pins dead function objects.  Default args
    # are state too (the taped double-grad bwd carries its fwd_fn there).
    ident = getattr(base, "__code__", base)
    dfrozen = _freeze(getattr(base, "__defaults__", None) or ())
    if dfrozen is _Unfreezable:
        return None
    cells = cells + (dfrozen[0],)
    if isinstance(fn, functools.partial):
        if fn.args:
            return None
        kwf = [(k, _freeze(v)) for k, v in sorted(fn.keywords.items())]
        if any(v is _Unfreezable for _, v in kwf):
            return None
        kw = tuple((k, v[0]) for k, v in kwf)
    else:
        kw = ()
    sig = []
    call_args = list(jax_args)
    for i, a in enumerate(jax_args):
        if isinstance(a, jax.Array):
            sig.append(("a", a.shape, str(a.dtype)))
        else:
            f = _freeze(a)
            if f is _Unfreezable:
                return None  # unkeyable static arg
            call_args[i] = f[1]  # what the cached jit receives (hashable)
            sig.append(("s", f[0]))
    key = (ident, cells, kw, tuple(diff_positions), tuple(sig))
    return key, call_args


def _build_dispatch(key, fn, jax_args, diff_positions):
    static_pos = tuple(i for i, a in enumerate(jax_args)
                       if not isinstance(a, jax.Array))
    fwd = jax.jit(lambda *a: fn(*a), static_argnums=static_pos)

    def bwd_impl(*args_and_ct):
        args, ct = args_and_ct[:-1], args_and_ct[-1]

        def g(*dv):
            call = list(args)
            for p, v in zip(diff_positions, dv):
                call[p] = v
            return fn(*call)

        _, vjp_fn = jax.vjp(g, *(args[p] for p in diff_positions))
        return vjp_fn(ct)

    bwd = jax.jit(bwd_impl, static_argnums=static_pos)
    return fwd, bwd


def _apply_op_impl(name: str, fn: Callable, args: Sequence[Any], n_outputs: int = 1):
    jax_args = []
    diff_positions = []
    tape_on = is_grad_enabled()
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            v = a._value
            jax_args.append(v)
            if tape_on and not a.stop_gradient and jnp.issubdtype(
                    jnp.result_type(v), jnp.inexact):
                diff_positions.append(i)
        else:
            jax_args.append(a)

    # AMP auto-cast preamble (ref eager_gen.py:363 generated AMP logic).
    global _amp_mod
    if _amp_mod is None:
        from .. import amp as _amp_mod_  # late import: amp depends on tensor
        _amp_mod = _amp_mod_
    if _amp_mod._amp_state() is not None:
        jax_args = _amp_mod.cast_inputs_for_op(name, jax_args)

    # cached-dispatch eligibility: not under an outer trace (there the
    # enclosing jit already caches), not a taped-engine grad op (the
    # create_graph backward re-applies node backwards whose state lives in
    # bound defaults; keep those on the always-retraced path), stable fn
    # identity, hashable statics
    dispatch = None
    if (not name.endswith("_grad")
            and not any(isinstance(a, jax.core.Tracer) for a in jax_args)):
        keyed = _dispatch_key(fn, jax_args, diff_positions)
        if keyed is not None and keyed[0][0] not in _dispatch_blacklist:
            key, jax_args = keyed  # statics now hashable (lists -> tuples)
            cache = _dispatch_cache_fresh()
            dispatch = cache.get(key)
            if dispatch is None:
                builds = _dispatch_builds.get(key[0], 0) + 1
                if builds > _DISPATCH_CHURN_LIMIT:
                    _dispatch_blacklist.add(key[0])  # churny op: retrace
                else:
                    _dispatch_builds[key[0]] = builds
                    if len(cache) >= _DISPATCH_CACHE_MAX:
                        cache.pop(next(iter(cache)))  # FIFO eviction
                    dispatch = _build_dispatch(key, fn, jax_args,
                                               diff_positions)
                    cache[key] = dispatch

    if not diff_positions:
        out = dispatch[0](*jax_args) if dispatch is not None else fn(*jax_args)
        return _wrap_outputs(name, out, n_outputs, node=None)

    const_args = list(jax_args)

    def closed(*diff_vals):
        call = list(const_args)
        for pos, val in zip(diff_positions, diff_vals):
            call[pos] = val
        return fn(*call)

    if dispatch is not None:
        out = dispatch[0](*jax_args)
        _bwd_jit = dispatch[1]

        def make_vjp(_single):
            def node_vjp(cotangents):
                with no_grad():
                    return _bwd_jit(
                        *jax_args,
                        cotangents[0] if _single else tuple(cotangents))
            return node_vjp
    else:
        diff_vals = [jax_args[i] for i in diff_positions]
        out, vjp_fn = jax.vjp(closed, *diff_vals)

        def make_vjp(_single, _vjp=vjp_fn):
            def node_vjp(cotangents):
                with no_grad():
                    return _vjp(cotangents[0] if _single else
                                tuple(cotangents))
            return node_vjp

    parents = []
    for pos in diff_positions:
        src = args[pos]
        if src._grad_node is not None:
            parents.append((src._grad_node, src._out_idx))
        else:
            # a double-grad snapshot stands in for its original leaf so
            # accumulation/hooks land on the user-visible tensor
            alias = getattr(src, "_leaf_alias", None)
            parents.append(_LeafSlot(alias if alias is not None else src))

    outs = out if isinstance(out, tuple) else (out,)
    out_avals = [(o.shape, o.dtype) for o in outs]
    node_vjp = make_vjp(not isinstance(out, tuple))

    node = GradNode(name, node_vjp, parents, len(outs), out_avals)
    if flags.flag("eager_retain_double_grad"):
        node.fwd_fn = closed
        # Snapshot the recorded input VALUES (ref TensorWrapper,
        # eager/tensor_wrapper.h): the re-taped backward recomputes the
        # forward from in_tensors inside jax.vjp, so holding the live
        # Tensor objects would silently diverge after any in-place update
        # (optimizer _set_value, fill_) between forward and grad.  The
        # snapshot keeps the original autograd metadata so second-order
        # chains still connect to the graph (jax arrays are immutable —
        # this aliases, never copies).
        snaps = []
        for pos in diff_positions:
            src = args[pos]
            snap = Tensor(jax_args[pos], stop_gradient=src.stop_gradient,
                          _grad_node=src._grad_node, _out_idx=src._out_idx)
            if src._grad_node is None:
                # leaf grads/hooks land on the original user-visible tensor
                # (resolve transitively: a snapshot of a snapshot — higher-
                # order re-tapes — must still alias the true leaf)
                base = getattr(src, "_leaf_alias", None)
                snap._leaf_alias = src if base is None else base
            snaps.append(snap)
        node.in_tensors = tuple(snaps)
    return _wrap_outputs(name, out, n_outputs, node=node)


def _wrap_outputs(name, out, n_outputs, node):
    if flags.flag("check_nan_inf"):
        _check_nan_inf(name, out if isinstance(out, tuple) else (out,))
    stop = node is None
    if isinstance(out, tuple):
        return tuple(
            Tensor(o, stop_gradient=stop, _grad_node=node, _out_idx=i)
            for i, o in enumerate(out))
    return Tensor(out, stop_gradient=stop, _grad_node=node, _out_idx=0)


def primitive(name: str):
    """Decorator turning a pure jax function into a taped framework op.

    The wrapped function receives jax values; the public wrapper accepts
    Tensors / scalars. Keyword arguments are static (non-differentiable) and
    folded into the closure.
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            call = functools.partial(fn, **kwargs) if kwargs else fn
            return apply_op(name, call, args)

        wrapper.__framework_op__ = name
        return wrapper

    return deco
