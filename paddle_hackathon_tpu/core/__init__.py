"""Core runtime: Tensor, autograd engine, device/place, dtypes, flags, RNG.

Equivalent of the reference's ``paddle/phi/core`` + ``paddle/fluid/eager`` +
``paddle/fluid/platform`` stack, collapsed onto JAX/PJRT (see SURVEY.md §7
phase 1).
"""

from . import autograd, device, dtype, flags, random
from .autograd import enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled
from .device import Place, current_place, get_device, set_device
from .tensor import Tensor, to_tensor
