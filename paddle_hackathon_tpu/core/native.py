"""ctypes bindings for the native C++ runtime core.

The reference implements its runtime services in C++ (allocator
``paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.cc``,
rendezvous store ``distributed/store/tcp_store.h:120``, async instruction
scheduling ``framework/new_executor/interpretercore.cc:653`` + ``workqueue/``,
host profiling ``platform/profiler/host_event_recorder.h``, flags
``platform/flags.cc``). This module builds and loads our native counterpart
(``native/runtime.cc``) on first use — compiled with g++ into a shared
library cached by source hash — and exposes Pythonic wrappers.

On TPU the device side (HBM, streams) is owned by XLA/PJRT, so the native
layer covers the host runtime: rendezvous, host staging memory, host DAG
scheduling, and instrumentation.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional, Sequence

from ..observability.sanitizers import make_lock

_SRC = Path(__file__).resolve().parent.parent / "native" / "runtime.cc"
_BUILD_DIR = _SRC.parent / "_build"

_lib = None
_lib_failed = False
_lib_lock = make_lock("core.native_build")
_TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int32)


def _build() -> Path:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _BUILD_DIR / f"libphtpu_{tag}.so"
    if out.exists():
        return out
    _BUILD_DIR.mkdir(exist_ok=True)
    tmp = out.with_suffix(".so.tmp%d" % os.getpid())
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-fvisibility=hidden", str(_SRC), "-o", str(tmp)]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)
    return out


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native runtime; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_failed:
            return None
        try:
            path = _build()
            lib = ctypes.CDLL(str(path))
        except Exception:
            _lib_failed = True  # remember; don't re-run g++ on every call
            return None
        _declare(lib)
        _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.pht_flag_set.argtypes = [c.c_char_p, c.c_char_p]
    lib.pht_flag_get.argtypes = [c.c_char_p, c.c_char_p, c.c_int32]
    lib.pht_flag_get.restype = c.c_int32
    lib.pht_alloc.argtypes = [c.c_uint64]
    lib.pht_alloc.restype = c.c_void_p
    lib.pht_free.argtypes = [c.c_void_p]
    lib.pht_mem_stat.argtypes = [c.c_int32]
    lib.pht_mem_stat.restype = c.c_int64
    lib.pht_mem_reset_peak.argtypes = []
    lib.pht_trace_enable.argtypes = [c.c_int32]
    lib.pht_trace_push.argtypes = [c.c_char_p]
    lib.pht_trace_pop.argtypes = []
    lib.pht_trace_record.argtypes = [c.c_char_p, c.c_int64, c.c_int64]
    lib.pht_trace_count.restype = c.c_int64
    lib.pht_trace_dump_chrome.argtypes = [c.c_char_p, c.c_int64]
    lib.pht_trace_dump_chrome.restype = c.c_int64
    lib.pht_wq_create.argtypes = [c.c_int32]
    lib.pht_wq_create.restype = c.c_void_p
    lib.pht_wq_destroy.argtypes = [c.c_void_p]
    lib.pht_wq_run_dag.argtypes = [c.c_void_p, c.c_int32, _TASK_FN,
                                   c.c_void_p, c.POINTER(c.c_int32),
                                   c.POINTER(c.c_int32), c.POINTER(c.c_int32),
                                   c.c_int32]
    lib.pht_store_server_start.argtypes = [c.c_int32]
    lib.pht_store_server_start.restype = c.c_void_p
    lib.pht_store_server_port.argtypes = [c.c_void_p]
    lib.pht_store_server_port.restype = c.c_int32
    lib.pht_store_server_stop.argtypes = [c.c_void_p]
    lib.pht_store_connect.argtypes = [c.c_char_p, c.c_int32, c.c_int32]
    lib.pht_store_connect.restype = c.c_void_p
    lib.pht_store_disconnect.argtypes = [c.c_void_p]
    lib.pht_store_set.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_uint8), c.c_int32]
    lib.pht_store_set.restype = c.c_int32
    lib.pht_store_get.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_uint8), c.c_int32, c.c_int64]
    lib.pht_store_get.restype = c.c_int32
    lib.pht_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pht_store_add.restype = c.c_int64
    lib.pht_reader_create.argtypes = [c.c_int32, c.c_int64]
    lib.pht_reader_create.restype = c.c_void_p
    lib.pht_reader_stage.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                     c.c_int64]
    lib.pht_reader_stage.restype = c.c_int32
    lib.pht_reader_next.argtypes = [c.c_void_p, c.POINTER(c.c_void_p),
                                    c.POINTER(c.c_int64), c.c_int64]
    lib.pht_reader_next.restype = c.c_int32
    lib.pht_reader_release.argtypes = [c.c_void_p, c.c_int32]
    lib.pht_reader_close.argtypes = [c.c_void_p]
    lib.pht_reader_destroy.argtypes = [c.c_void_p]
    lib.pht_store_check.argtypes = [c.c_void_p, c.c_char_p]
    lib.pht_store_check.restype = c.c_int32
    lib.pht_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pht_store_delete.restype = c.c_int32


# ---------------------------------------------------------------------------
# Memory (host staging allocator; ref memory/stats.h DEVICE_MEMORY_STAT_*)
# ---------------------------------------------------------------------------

class HostAllocation:
    """An aligned host buffer from the native auto-growth best-fit
    allocator — the staging-buffer analog of the reference's pinned host
    allocations (``memory/allocation/pinned_allocator.cc``)."""

    def __init__(self, nbytes: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self.nbytes = nbytes
        self.ptr = lib.pht_alloc(nbytes)
        if not self.ptr:
            raise MemoryError(f"pht_alloc({nbytes}) failed")

    def as_numpy(self, dtype, shape):
        import numpy as np
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if n > self.nbytes:
            raise ValueError("buffer too small")
        buf = (ctypes.c_char * self.nbytes).from_address(self.ptr)
        buf._owner = self  # keep the allocation alive through the view chain
        return np.frombuffer(buf, dtype=dtype,
                             count=int(np.prod(shape))).reshape(shape)

    def free(self):
        if self.ptr:
            self._lib.pht_free(self.ptr)
            self.ptr = None

    def __del__(self):
        try:
            self.free()
        except Exception:
            pass


def memory_stats() -> dict:
    """Host allocator counters (ref ``memory/stats.h:112`` peak/current)."""
    lib = load()
    if lib is None:
        return {}
    return {
        "current_in_use": lib.pht_mem_stat(0),
        "peak_in_use": lib.pht_mem_stat(1),
        "reserved": lib.pht_mem_stat(2),
        "alloc_count": lib.pht_mem_stat(3),
        "free_count": lib.pht_mem_stat(4),
    }


def reset_peak_memory_stats() -> None:
    lib = load()
    if lib is not None:
        lib.pht_mem_reset_peak()


# ---------------------------------------------------------------------------
# WorkQueue (ref new_executor dependency-counted scheduling)
# ---------------------------------------------------------------------------

class WorkQueue:
    """Dependency-counted DAG executor over a native thread pool.

    The TPU-native analog of the standalone executor's instruction
    scheduler (``interpretercore.cc:653`` ``ExecuteInstructionList`` with
    ``RunNextInstructions:710``): tasks become ready when their predecessor
    count reaches zero; worker threads drain the ready queue concurrently.
    Used for host-side work (dataloader pipelines, multi-program dispatch);
    device-side scheduling belongs to XLA's latency-hiding scheduler.
    """

    def __init__(self, num_threads: int = 4):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._wq = lib.pht_wq_create(num_threads)

    def run_dag(self, tasks: Sequence, successors: Sequence[Sequence[int]],
                trace: bool = False):
        """Run callables honouring the DAG: ``successors[i]`` lists task
        indices that depend on task i. Blocks until all tasks ran."""
        n = len(tasks)
        if n == 0:
            return
        if len(successors) != n:
            raise ValueError("successors must have one entry per task")
        dep = [0] * n
        for succs in successors:
            for s in succs:
                dep[s] += 1
        adj, off = [], [0]
        for succs in successors:
            adj.extend(succs)
            off.append(len(adj))
        errors = []

        def runner(_arg, idx):
            try:
                tasks[idx]()
            except BaseException as e:  # propagate after the run
                errors.append((idx, e))

        cb = _TASK_FN(runner)
        c_dep = (ctypes.c_int32 * n)(*dep)
        c_adj = (ctypes.c_int32 * max(len(adj), 1))(*(adj or [0]))
        c_off = (ctypes.c_int32 * (n + 1))(*off)
        self._lib.pht_wq_run_dag(self._wq, n, cb, None, c_dep, c_adj, c_off,
                                 1 if trace else 0)
        if errors:
            idx, err = errors[0]
            raise RuntimeError(f"workqueue task {idx} failed: {err!r}") from err

    def map(self, fn, items, trace: bool = False):
        """Independent-task convenience: run fn over items concurrently."""
        out = [None] * len(items)

        def make(i):
            def task():
                out[i] = fn(items[i])
            return task

        self.run_dag([make(i) for i in range(len(items))],
                     [[] for _ in items], trace=trace)
        return out

    def close(self):
        if self._wq:
            self._lib.pht_wq_destroy(self._wq)
            self._wq = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Native host tracer (ref platform/profiler host_event_recorder.h)
# ---------------------------------------------------------------------------

def trace_enable(on: bool = True) -> None:
    lib = load()
    if lib is not None:
        lib.pht_trace_enable(1 if on else 0)


def trace_push(name: str) -> None:
    lib = load()
    if lib is not None:
        lib.pht_trace_push(name.encode())


def trace_pop() -> None:
    lib = load()
    if lib is not None:
        lib.pht_trace_pop()


def trace_count() -> int:
    lib = load()
    return int(lib.pht_trace_count()) if lib is not None else 0


def trace_clear() -> None:
    lib = load()
    if lib is not None:
        lib.pht_trace_clear()


def trace_dump_chrome(path: str, pid: Optional[int] = None) -> int:
    """Dump native events as chrome://tracing JSON (ref
    ``chrometracing_logger.cc``); returns event count."""
    lib = load()
    if lib is None:
        return 0
    return int(lib.pht_trace_dump_chrome(path.encode(),
                                         pid if pid is not None else os.getpid()))


def sync_flags(flags: dict) -> None:
    """Mirror Python-side flags into the native registry so C++ components
    observe them (ref global_value_getter_setter.cc round-trip)."""
    lib = load()
    if lib is None:
        return
    for k, v in flags.items():
        lib.pht_flag_set(str(k).encode(), str(v).encode())


def flag_get(name: str) -> Optional[str]:
    lib = load()
    if lib is None:
        return None
    buf = ctypes.create_string_buffer(4096)
    n = lib.pht_flag_get(name.encode(), buf, 4096)
    if n < 0:
        return None
    return buf.value.decode()


class StagingRing:
    """Native staging ring for DataLoader batches (ref buffered_reader.cc).

    Producer threads call :meth:`stage` (the batch memcpy runs in C++ with
    the GIL released); the consumer pops in sequence order with
    :meth:`next` and returns slots via :meth:`release`.
    """

    def __init__(self, n_slots: int = 4, slot_bytes: int = 1 << 20):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._ring = lib.pht_reader_create(n_slots, slot_bytes)

    def stage(self, array, seq: int) -> int:
        import numpy as np
        a = np.ascontiguousarray(array)
        return self._lib.pht_reader_stage(
            self._ring, a.ctypes.data_as(ctypes.c_void_p), a.nbytes, seq)

    def next(self, dtype, shape, timeout_ms: int = 60000):
        """Pop the next staged block viewed as (dtype, shape) numpy array.
        Returns (slot, array-copy-free-view) or (None, None) when drained."""
        import numpy as np
        ptr = ctypes.c_void_p()
        nbytes = ctypes.c_int64()
        slot = self._lib.pht_reader_next(self._ring, ctypes.byref(ptr),
                                         ctypes.byref(nbytes), timeout_ms)
        if slot == -1:
            raise TimeoutError("staging ring timed out")
        if slot == -2:
            return None, None
        n = nbytes.value
        buf = (ctypes.c_char * n).from_address(ptr.value)
        arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
        return slot, arr

    def release(self, slot: int) -> None:
        self._lib.pht_reader_release(self._ring, slot)

    def close(self) -> None:
        if getattr(self, "_ring", None):
            self._lib.pht_reader_close(self._ring)

    def __del__(self):
        try:
            self.close()
            if getattr(self, "_ring", None):
                self._lib.pht_reader_destroy(self._ring)
                self._ring = None
        except Exception:
            pass
