"""StringTensor + strings kernels (the last §2 inventory row).

Ref ``paddle/phi/core/string_tensor.h`` (StringTensor over pstring
payloads), ``phi/api/yaml/strings_api.yaml`` (empty / empty_like /
lower / upper) and ``phi/kernels/strings/strings_lower_upper_kernel.h``
(ASCII fast path vs ``use_utf8_encoding`` unicode path), with the
eager constructor surface of ``core.eager.StringTensor``
(``test_egr_string_tensor_api.py``).

TPU-native design note: strings are HOST data here exactly as in the
reference (its string kernels are CPU/GPU-host utilities, never MXU
work) — the payload is a numpy unicode array; nothing is staged to the
accelerator.
"""

from __future__ import annotations

import numpy as np

from ..utils import unique_name

__all__ = ["StringTensor", "strings_empty", "strings_empty_like",
           "strings_lower", "strings_upper"]

_ASCII_LOWER = str.maketrans(
    {c: chr(ord(c) + 32) for c in "ABCDEFGHIJKLMNOPQRSTUVWXYZ"})
_ASCII_UPPER = str.maketrans(
    {c: chr(ord(c) - 32) for c in "abcdefghijklmnopqrstuvwxyz"})


class StringTensor:
    """A tensor of strings (host-resident).

    Constructors mirror ``core.eager.StringTensor``:
    ``StringTensor()`` (empty scalar), ``StringTensor([2, 3])`` (empty
    of shape), ``StringTensor(ndarray_or_nested_list)``,
    ``StringTensor(other_string_tensor)`` (copy); all accept an
    optional ``name``.
    """

    def __init__(self, value=None, name: str | None = None):
        if value is None:
            arr = np.asarray("", dtype=np.str_)
        elif isinstance(value, StringTensor):
            arr = value._value.copy()
        elif (isinstance(value, (list, tuple)) and value
              and all(isinstance(v, (int, np.integer)) for v in value)):
            arr = np.empty(tuple(int(v) for v in value), dtype=np.str_)
        else:
            arr = np.asarray(value, dtype=np.str_)
        self._value = arr
        self.name = (name if name is not None
                     else unique_name.generate("generated_string_tensor"))

    @property
    def shape(self):
        return list(self._value.shape)

    def numpy(self):
        v = self._value
        # scalar StringTensor mirrors the reference: numpy() is the str
        return v.item() if v.ndim == 0 else v

    def _map(self, fn):
        flat = [fn(s) for s in self._value.reshape(-1)]
        out = np.asarray(flat, dtype=np.str_).reshape(self._value.shape) \
            if flat else np.empty(self._value.shape, np.str_)
        return StringTensor(out)

    def lower(self, use_utf8_encoding: bool = False) -> "StringTensor":
        """ref strings_api.yaml ``lower``: ASCII-only case map by
        default; ``use_utf8_encoding=True`` applies the full unicode
        case conversion (the reference's unicode.h path)."""
        if use_utf8_encoding:
            return self._map(str.lower)
        return self._map(lambda s: s.translate(_ASCII_LOWER))

    def upper(self, use_utf8_encoding: bool = False) -> "StringTensor":
        if use_utf8_encoding:
            return self._map(str.upper)
        return self._map(lambda s: s.translate(_ASCII_UPPER))

    def __repr__(self):
        return (f"StringTensor(name={self.name!r}, shape={self.shape}, "
                f"{self._value!r})")


def strings_empty(shape) -> StringTensor:
    """ref strings_api.yaml ``empty``."""
    return StringTensor(list(shape) if shape else None)


def strings_empty_like(x: StringTensor) -> StringTensor:
    return StringTensor(list(x.shape) if x.shape else None)


def strings_lower(x: StringTensor, use_utf8_encoding: bool = False):
    return x.lower(use_utf8_encoding)


def strings_upper(x: StringTensor, use_utf8_encoding: bool = False):
    return x.upper(use_utf8_encoding)
