"""Runtime kernel autotuning with a result cache.

Reference: ``paddle/phi/kernels/autotune/`` — ``auto_tune_base.h`` measures
candidate algorithms for a kernel signature once and caches the winner
(``cache.h``), gated by ``switch_autotune.cc`` and configured from python
via ``paddle.incubate.autotune`` (``python/paddle/incubate/autotune.py``).

TPU-native scope: XLA autotunes its own fusions inside the compiler, so
the tunable surface here is the Pallas kernels' launch parameters (block
shapes). Tuning runs in eager mode only — under a jit trace there is
nothing to measure — which mirrors the reference's dygraph warmup-step
tuning window; the cached winner is then used by traced/compiled calls.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Hashable, List, Optional, Tuple

__all__ = ["AutoTuneCache", "kernel_cache", "enabled", "in_tuning_window",
           "set_config", "step", "status", "tune"]

_config = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}
_step_count = 0


class AutoTuneCache:
    """Winner cache keyed by an arbitrary hashable kernel signature
    (ref ``cache.h`` AlgorithmsCache)."""

    def __init__(self):
        self._cache: Dict[Hashable, object] = {}
        self.hits = 0
        self.misses = 0

    def get(self, key):
        if key in self._cache:
            self.hits += 1
            return self._cache[key]
        self.misses += 1
        return None

    def put(self, key, value):
        self._cache[key] = value

    def size(self):
        return len(self._cache)

    def clear(self):
        self._cache.clear()
        self.hits = self.misses = 0


kernel_cache = AutoTuneCache()


def set_config(config: Optional[dict] = None):
    """Apply a ``paddle.incubate.autotune``-style config
    (ref ``incubate/autotune.py`` set_config): a dict — or a JSON file
    path, as the reference accepts — with keys 'kernel'
    ({enable, tuning_range}), 'layout', 'dataloader'. Enabling kernel
    tuning resets the step counter so the tuning window is relative to
    now (the reference counts from training start)."""
    global _config, _step_count
    if config is None:
        _config["kernel"]["enable"] = True
        _step_count = 0
        return
    if isinstance(config, str):
        import json
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(
            f"autotune config must be a dict or JSON file path, got "
            f"{type(config)}")
    for section in ("kernel", "layout", "dataloader"):
        if section in config:
            _config[section].update(config[section])
    if config.get("kernel", {}).get("enable"):
        _step_count = 0


def enabled() -> bool:
    return bool(_config["kernel"]["enable"])


def in_tuning_window() -> bool:
    lo, hi = _config["kernel"].get("tuning_range", [1, 10])
    return lo <= _step_count <= hi


def step():
    """Advance the autotune step counter (called from optimizer.step);
    tuning only happens inside the configured step range."""
    global _step_count
    _step_count += 1


def status() -> dict:
    return {"config": _config, "step": _step_count,
            "cache_size": kernel_cache.size(),
            "hits": kernel_cache.hits, "misses": kernel_cache.misses}


def tune(key: Hashable, candidates: List, measure: Callable[[object], float],
         default=None):
    """Measure every candidate once, cache and return the fastest
    (ref ``auto_tune_base.h`` AutoTuneBase::PickBestAlgorithm).

    ``measure(candidate) -> seconds`` should include a device sync; a
    candidate that raises is skipped. Returns ``default`` (or the first
    candidate) when tuning is disabled or everything fails.
    """
    cached = kernel_cache.get(key)
    if cached is not None:
        return cached
    if not candidates:
        if default is None:
            raise ValueError(f"autotune: no viable candidates for {key!r} "
                             "and no default")
        kernel_cache.put(key, default)
        return default
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            t = measure(cand)
        except Exception:
            continue
        if t < best_t:
            best, best_t = cand, t
    if best is None:
        best = default if default is not None else candidates[0]
    kernel_cache.put(key, best)
    return best


def measure_wall(fn: Callable[[], None], reps: int = 3) -> float:
    """Median wall time of ``fn()`` over ``reps`` runs (fn must sync)."""
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]
