"""Dtype table and promotion helpers.

Equivalent of the reference's ``paddle/phi/common/data_type.h`` dtype enum and the
per-op dtype plumbing in ``phi/api/lib/kernel_dispatch.h``. On TPU the canonical
floating type is bfloat16 (MXU-native); float32 stays the default user-facing
dtype, matching the reference's defaults.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import flags

# Public dtype aliases (paddle.float32 etc.)
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}


def convert_dtype(dtype):
    """Normalise str/np/jnp dtype spellings to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _STR2DTYPE[dtype]
        except KeyError:
            raise ValueError(f"unknown dtype: {dtype!r}") from None
    return jnp.dtype(dtype).type


def default_float_dtype():
    return convert_dtype(flags.flag("default_dtype"))


def set_default_dtype(dtype):
    """paddle.set_default_dtype equivalent."""
    d = convert_dtype(dtype)
    if d not in (float16, bfloat16, float32, float64):
        raise ValueError("default dtype must be a floating dtype")
    flags.set_flags({"default_dtype": np.dtype(d).name})


def get_default_dtype() -> str:
    return flags.flag("default_dtype")


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)
