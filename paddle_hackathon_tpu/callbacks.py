"""paddle.callbacks namespace (ref ``python/paddle/callbacks.py``) — hapi
training callbacks."""

from .hapi.callbacks import (Callback, EarlyStopping,  # noqa: F401
                             LRScheduler, ModelCheckpoint, ProgBarLogger)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau"]


class VisualDL(Callback):
    """Scalar logger (ref callbacks VisualDL — visualdl isn't bundled, so
    scalars append to a jsonl the dashboard can tail)."""

    def __init__(self, log_dir):
        import os
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None

    def on_train_begin(self, logs=None):
        import os
        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def on_epoch_end(self, epoch, logs=None):
        import json
        if self._f and logs:
            rec = {"epoch": epoch}
            rec.update({k: float(v) for k, v in logs.items()
                        if isinstance(v, (int, float))})
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


class ReduceLROnPlateau(Callback):
    """Reduce optimizer LR when a monitored metric stalls
    (ref callbacks ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0
        if mode == "auto":  # infer like the reference: acc/auc grow
            mode = ("max" if any(k in monitor for k in ("acc", "auc"))
                    else "min")
        self.mode = mode
        self._stepped_this_epoch = False

    def _better(self, cur, best):
        if self.mode == "max":
            return cur > best + self.min_delta
        return cur < best - self.min_delta

    def on_epoch_begin(self, epoch, logs=None):
        self._stepped_this_epoch = False

    def on_eval_end(self, logs=None):
        # eval metrics take priority over the train logs of the same epoch
        self._step(logs)
        self._stepped_this_epoch = True

    def on_epoch_end(self, epoch, logs=None):
        if not self._stepped_this_epoch:
            self._step(logs)

    def _step(self, logs):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
            return  # hold during cooldown
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = getattr(getattr(self, "model", None), "_optimizer", None)
            if opt is not None:
                lr = max(float(opt.get_lr()) * self.factor, self.min_lr)
                opt.set_lr(lr)
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr -> {lr}")
            self.wait = 0
            self.cooldown_counter = self.cooldown
